"""Compile-ahead layer acceptance battery (ops/compile_cache.py, ISSUE 5).

Covers: the persistent executable store (round-trip, validation, size cap),
executor disk reuse across instances, the warmup API + shape-profile
manifests, chaos degradation of a poisoned cache (corrupt / stale /
wrong-computation entries -> warning + fresh compile, never a crash or a
wrong result), stall-free background compilation (eager-miss swap-in,
concurrency, rollback/recovery interplay, exactness per state family), and
the env-flag escape hatches.

The suite-wide conftest sets ``TORCHMETRICS_TPU_COMPILE_AHEAD=0``; every
test here re-enables the layer explicitly against a tmp cache dir.
"""
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export as jax_export

from torchmetrics_tpu import MeanMetric, MetricCollection
from torchmetrics_tpu.aggregation import CatMetric, MaxMetric, MinMetric, SumMetric
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)
from torchmetrics_tpu.ops import compile_cache
from torchmetrics_tpu.ops.executor import executor_stats
from torchmetrics_tpu.testing import faults

NUM_CLASSES = 5


@pytest.fixture()
def cache_env(monkeypatch, tmp_path):
    """Compile-ahead ON against an isolated store; returns the cache dir."""
    cache_dir = tmp_path / "tm_cache"
    monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "1")
    monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(cache_dir))
    return cache_dir


def _mc_batch(n, seed=0):
    r = np.random.RandomState(seed)
    return (
        jnp.asarray(r.randn(n, NUM_CLASSES).astype(np.float32)),
        jnp.asarray(r.randint(0, NUM_CLASSES, n)),
    )


def _entries(cache_dir):
    store = os.path.join(str(cache_dir), "executables")
    if not os.path.isdir(store):
        return []
    return sorted(p for p in os.listdir(store) if p.endswith(compile_cache.ENTRY_SUFFIX))


def _populate(cache_dir, n=32, seed=0):
    """Run one metric through the executor and wait for its persist job."""
    m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
    preds, target = _mc_batch(n, seed)
    m.update(preds, target)
    assert compile_cache.drain_worker(90)
    assert _entries(cache_dir), "persist job wrote no entry"
    return m, float(m.compute())


# --------------------------------------------------------------------- store

class TestStore:
    def test_blob_round_trip(self, cache_env):
        blobs = [(compile_cache.FORMAT_COMPILED, b"native" * 100), (compile_cache.FORMAT_STABLEHLO, b"hlo" * 50)]
        path = compile_cache.store_executable("some|key|desc", blobs)
        assert path is not None and os.path.exists(path)
        assert compile_cache.load_executable_blob("some|key|desc") == blobs

    def test_key_desc_mismatch_is_a_miss(self, cache_env):
        compile_cache.store_executable("key-a", (compile_cache.FORMAT_COMPILED, b"blob-a"))
        assert compile_cache.load_executable_blob("key-b") is None

    @pytest.mark.parametrize("mode", ["truncate", "zero", "flip", "garbage"])
    def test_corrupt_entry_skipped_with_warning_and_deleted(self, cache_env, mode):
        compile_cache.store_executable("key", (compile_cache.FORMAT_COMPILED, b"x" * 4096))
        faults.corrupt_cache_entry(str(cache_env), mode=mode, which="all")
        with pytest.warns(UserWarning, match="damaged/stale entry"):
            assert compile_cache.load_executable_blob("key") is None
        assert not _entries(cache_env), "damaged entry must be deleted"

    def test_stale_toolchain_skipped_with_warning(self, cache_env):
        compile_cache.store_executable("key", (compile_cache.FORMAT_COMPILED, b"x" * 512))
        faults.stale_cache_version(str(cache_env))
        with pytest.warns(UserWarning, match="stale toolchain"):
            assert compile_cache.load_executable_blob("key") is None
        assert not _entries(cache_env)

    def test_size_cap_evicts_oldest(self, cache_env, monkeypatch):
        for i in range(6):
            compile_cache.store_executable(f"key-{i}", (compile_cache.FORMAT_COMPILED, bytes(2048)))
            time.sleep(0.01)  # distinct mtimes for deterministic eviction order
        store = os.path.join(str(cache_env), "executables")
        assert len(_entries(cache_env)) == 6
        # entry headers embed a JSON float timestamp, so sizes vary by a byte
        # or two: cap at the exact total of the 3 NEWEST entries (by mtime)
        by_mtime = sorted(
            (os.path.join(store, p) for p in _entries(cache_env)), key=os.path.getmtime
        )
        cap = sum(os.path.getsize(p) for p in by_mtime[3:])
        removed = compile_cache.prune_store(store, max_bytes=cap)
        assert removed == 3
        assert compile_cache.load_executable_blob("key-5") is not None  # newest survives
        assert compile_cache.load_executable_blob("key-0") is None  # oldest evicted

    def test_disabled_layer_stores_nothing(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "0")
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(tmp_path / "c"))
        assert compile_cache.cache_dir() is None
        assert compile_cache.store_executable("k", (compile_cache.FORMAT_COMPILED, b"b")) is None
        assert compile_cache.load_executable_blob("k") is None


# ----------------------------------------------------------------- env flags

class TestEnvFlags:
    def test_compile_ahead_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "0")
        assert not compile_cache.compile_ahead_enabled()
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "1")
        assert compile_cache.compile_ahead_enabled()

    def test_cache_dir_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "1")
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(tmp_path / "custom"))
        assert compile_cache.cache_dir() == str(tmp_path / "custom")
        monkeypatch.delenv("TORCHMETRICS_TPU_CACHE_DIR")
        assert compile_cache.cache_dir().endswith(os.path.join(".cache", "torchmetrics_tpu"))

    def test_bg_compile_env_default(self, monkeypatch):
        monkeypatch.delenv("TORCHMETRICS_TPU_BG_COMPILE", raising=False)
        assert not compile_cache.background_compile_default()
        monkeypatch.setenv("TORCHMETRICS_TPU_BG_COMPILE", "1")
        assert compile_cache.background_compile_default()
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        assert m._get_executor().background_enabled()

    def test_no_disk_io_when_disabled(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "0")
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(tmp_path / "never"))
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_mc_batch(32))
        compile_cache.drain_worker(30)
        assert not (tmp_path / "never").exists()
        assert executor_stats(m)["disk_stores"] == 0


# ---------------------------------------------------------------- disk reuse

class TestDiskReuse:
    def test_sibling_instance_loads_from_disk(self, cache_env):
        m1, v1 = _populate(cache_env)
        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m2.update(*_mc_batch(32))
        s2 = executor_stats(m2)
        assert s2["disk_hits"] == 1 and s2["compiles"] == 0
        assert float(m2.compute()) == v1

    def test_disk_loaded_executable_matches_eager(self, cache_env):
        _populate(cache_env)
        m_disk = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m_eager = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        for seed in range(4):
            batch = _mc_batch(32, seed)
            m_disk.update(*batch)
            m_eager.update(*batch)
        assert executor_stats(m_disk)["disk_hits"] == 1
        assert np.allclose(np.asarray(m_disk.compute()), np.asarray(m_eager.compute()))

    def test_different_config_is_a_different_key(self, cache_env):
        _populate(cache_env)
        m3 = MulticlassAccuracy(num_classes=NUM_CLASSES + 2, validate_args=False)
        r = np.random.RandomState(0)
        m3.update(jnp.asarray(r.randn(32, NUM_CLASSES + 2).astype(np.float32)), jnp.asarray(r.randint(0, NUM_CLASSES + 2, 32)))
        s3 = executor_stats(m3)
        assert s3["disk_hits"] == 0 and s3["compiles"] == 1

    def test_spec_round_trip(self):
        spec = compile_cache.spec_of_call("update", _mc_batch(16) + (True, 3), {"w": jnp.ones(16)})
        args, kwargs = compile_cache.dummy_from_spec(spec)
        assert args[0].shape == (16, NUM_CLASSES) and args[2] is True and args[3] == 3
        assert kwargs["w"].shape == (16,)
        # non-replayable structures are declined, not mangled
        assert compile_cache.spec_of_call("update", (([jnp.ones(2)],),), {}) is None


# -------------------------------------------------------------------- warmup

class TestWarmup:
    def test_warmup_makes_first_call_warm(self, cache_env):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        spec = (jax.ShapeDtypeStruct((32, NUM_CLASSES), jnp.float32), jax.ShapeDtypeStruct((32,), jnp.int32))
        report = m.warmup(spec, ladder=False)
        assert report["warmed"] == 1 and not report["skipped"]
        m.update(*_mc_batch(32))
        s = executor_stats(m)
        assert s["cache_hits"] == 1 and s["calls"] == 1 and s["warmup"] == 1

    def test_ladder_covers_ragged_batches(self, cache_env):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        report = m.warmup((jax.ShapeDtypeStruct((32, NUM_CLASSES), jnp.float32), jax.ShapeDtypeStruct((32,), jnp.int32)))
        assert report["warmed"] >= 3  # exact 32 + padded rungs 8/16/32
        compiles_after_warmup = executor_stats(m)["compiles"]
        for n in (32, 20, 9, 5):  # full + ragged sizes inside the warmed ladder
            m.update(*_mc_batch(n, seed=n))
        s = executor_stats(m)
        assert s["compiles"] == compiles_after_warmup, "ragged traffic recompiled despite ladder warmup"

    def test_warmup_never_touches_state(self, cache_env):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_mc_batch(32))
        before = float(m.compute())
        m.warmup((jax.ShapeDtypeStruct((64, NUM_CLASSES), jnp.float32), jax.ShapeDtypeStruct((64,), jnp.int32)))
        assert float(m.compute()) == before
        assert m.update_count == 1

    def test_background_warmup_handle(self, cache_env):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        handle = m.warmup(
            (jax.ShapeDtypeStruct((16, NUM_CLASSES), jnp.float32), jax.ShapeDtypeStruct((16,), jnp.int32)),
            ladder=False,
            background=True,
        )
        report = handle.wait(120)
        assert handle.done and report["warmed"] == 1

    def test_warmup_with_executor_disabled_reports_skip(self, cache_env):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        report = m.warmup(_mc_batch(8))
        assert report["warmed"] == 0 and report["skipped"] == ["executor disabled"]

    def test_collection_warmup_update_and_forward(self, cache_env):
        coll = MetricCollection(
            {
                "f1": MulticlassF1Score(num_classes=NUM_CLASSES, validate_args=False),
                "precision": MulticlassPrecision(num_classes=NUM_CLASSES, validate_args=False),
                "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
            }
        )
        spec = (jax.ShapeDtypeStruct((64, NUM_CLASSES), jnp.float32), jax.ShapeDtypeStruct((64,), jnp.int32))
        report = coll.warmup([spec], forward=True, ladder=False)
        assert report["warmed"] == 2 and not report["skipped"]  # fused update + fused forward
        batch = _mc_batch(64)
        coll.update(*batch)
        out = coll(*batch)
        s = executor_stats(coll)
        assert s["cache_hits"] == 2 and s["compiles"] == 2  # warmup compiled, traffic hit
        ref = MetricCollection(
            {
                "f1": MulticlassF1Score(num_classes=NUM_CLASSES, validate_args=False),
                "precision": MulticlassPrecision(num_classes=NUM_CLASSES, validate_args=False),
                "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
            },
            executor=False,
        )
        ref.update(*batch)
        ref_out = ref(*batch)
        for k in ref_out:
            assert np.allclose(np.asarray(out[k]), np.asarray(ref_out[k]))

    def test_manifest_records_and_replays(self, cache_env, tmp_path):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_mc_batch(32))
        m.update(*_mc_batch(20, seed=1))  # a ragged bucket the profile must carry
        manifest = m.shape_profile()
        assert len(manifest["specs"]) == 2
        path = str(tmp_path / "profile.json")
        m.save_shape_profile(path)

        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        report = m2.warmup_from_manifest(path)
        assert report["warmed"] >= 1
        compiles = executor_stats(m2)["compiles"] + executor_stats(m2)["disk_hits"]
        m2.update(*_mc_batch(32))
        m2.update(*_mc_batch(20, seed=1))
        s2 = executor_stats(m2)
        assert s2["compiles"] + s2["disk_hits"] == compiles, "manifest replay missed a bucket the run used"

    def test_collection_manifest_resolves_groups(self, cache_env, tmp_path):
        def build():
            return MetricCollection(
                {
                    "f1": MulticlassF1Score(num_classes=NUM_CLASSES, validate_args=False),
                    "recall": MulticlassRecall(num_classes=NUM_CLASSES, validate_args=False),
                }
            )

        coll = build()
        coll.update(*_mc_batch(16))
        coll.update(*_mc_batch(16, seed=2))  # second update engages the fused executor
        path = str(tmp_path / "coll_profile.json")
        coll.save_shape_profile(path)
        coll2 = build()
        report = coll2.warmup_from_manifest(path)
        assert report["warmed"] >= 1
        assert coll2._groups_checked  # manifest replay resolved compute groups


# --------------------------------------------------------------------- chaos

class TestPoisonedCacheChaos:
    """Satellite: a poisoned disk cache degrades to a fresh compile with a
    warning — never a crash, never a wrong result."""

    @pytest.mark.parametrize("mode", ["flip", "truncate", "garbage"])
    def test_corrupt_entry_degrades_to_fresh_compile(self, cache_env, mode):
        _, v1 = _populate(cache_env)
        faults.corrupt_cache_entry(str(cache_env), mode=mode, which="all")
        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        with pytest.warns(UserWarning, match="recompiling fresh"):
            m2.update(*_mc_batch(32))
        s2 = executor_stats(m2)
        assert s2["disk_hits"] == 0 and s2["compiles"] == 1
        assert s2["disabled_reason"] is None  # executor stays engaged
        assert float(m2.compute()) == v1

    def test_stale_version_degrades_to_fresh_compile(self, cache_env):
        _, v1 = _populate(cache_env)
        faults.stale_cache_version(str(cache_env), which="all")
        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        with pytest.warns(UserWarning, match="stale toolchain"):
            m2.update(*_mc_batch(32))
        s2 = executor_stats(m2)
        assert s2["disk_hits"] == 0 and s2["compiles"] == 1
        assert float(m2.compute()) == v1

    def test_wrong_computation_entry_evicted_at_dispatch(self, cache_env):
        """An entry that deserializes fine but holds a DIFFERENT computation
        (hash-collision / key-logic-drift stand-in): its dispatch failure
        evicts the entry and recompiles fresh — no sticky eager fallback."""
        m1, v1 = _populate(cache_env)
        # overwrite the real entry's payload with an export of the wrong signature
        ex = m1._get_executor()
        key_desc = ex._key_desc(next(iter(ex._cache)))
        wrong = jax_export.export(jax.jit(lambda x: x + 1))(jax.ShapeDtypeStruct((3,), jnp.float32))
        compile_cache.store_executable(key_desc, (compile_cache.FORMAT_STABLEHLO, bytes(wrong.serialize())))

        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        with pytest.warns(UserWarning, match="failed at dispatch"):
            m2.update(*_mc_batch(32))
        s2 = executor_stats(m2)
        assert s2["disk_evictions"] == 1 and s2["compiles"] == 1
        assert s2["disabled_reason"] is None
        assert float(m2.compute()) == v1
        # the poisoned bytes are gone; after the fresh compile's background
        # persist, whatever lives under that key (if anything) must be the
        # GOOD computation again — a third instance proves it end to end
        assert compile_cache.drain_worker(90)
        current = compile_cache.load_executable_blob(key_desc)
        assert current is None or all(blob != bytes(wrong.serialize()) for _, blob in current)
        m3 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m3.update(*_mc_batch(32))
        assert float(m3.compute()) == v1

    def test_unwritable_store_never_fatal(self, cache_env, monkeypatch):
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", "/proc/definitely/not/writable")
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_mc_batch(32))  # must not raise
        compile_cache.drain_worker(60)
        assert executor_stats(m)["calls"] == 1


# ---------------------------------------------------- background compilation

def _swap_in(metric, batch, timeout=90.0):
    """Wait until the background-compiled executable for ``batch`` swapped in."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if executor_stats(metric)["pending_background"] == 0 and executor_stats(metric)["background_compiles"] > 0:
            return
        time.sleep(0.01)
    raise AssertionError("background compile never swapped in")


class TestBackgroundCompile:
    def test_miss_dispatches_eagerly_then_swaps_in(self, cache_env):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.set_background_compile(True)
        batch = _mc_batch(32)
        m.update(*batch)  # cold key: eager body serves the step
        s = executor_stats(m)
        assert s["eager_misses"] >= 1 and s["calls"] == 0
        assert m.update_count == 1  # the step itself committed
        _swap_in(m, batch)
        m.update(*batch)
        s = executor_stats(m)
        assert s["calls"] == 1 and s["cache_hits"] == 1 and s["background_compiles"] == 1

    @pytest.mark.parametrize(
        "family,build,batches",
        [
            # nan_strategy="ignore": "warn"/"error" need concrete values and
            # statically opt out of the executor (aggregation._executor_traceable)
            ("sum", lambda: SumMetric(nan_strategy="ignore"), [jnp.arange(8.0), jnp.arange(8.0) * 2]),
            ("mean", lambda: MeanMetric(nan_strategy="ignore"), [jnp.arange(8.0), jnp.ones(8)]),
            ("max", lambda: MaxMetric(nan_strategy="ignore"), [jnp.arange(8.0), -jnp.arange(8.0)]),
            ("min", lambda: MinMetric(nan_strategy="ignore"), [jnp.arange(8.0), -jnp.arange(8.0)]),
            ("cat", lambda: CatMetric(), [jnp.arange(4.0), jnp.arange(4.0) + 9]),
        ],
    )
    def test_exactness_per_state_family(self, cache_env, family, build, batches):
        """The full stream — eager-miss steps, then swapped-in compiled steps
        — must match the pure eager path bit-for-bit per state family (cat is
        list-state: statically ineligible, the mode must still be harmless)."""
        m_bg, m_eager = build(), build()
        m_eager._executor_enabled = False
        m_bg.set_background_compile(True)
        for b in batches:
            m_bg.update(b)
            m_eager.update(b)
        compile_cache.drain_worker(90)
        for b in batches:  # second pass: warm (or still-eager for cat)
            m_bg.update(b)
            m_eager.update(b)
        assert np.allclose(np.asarray(m_bg.compute()), np.asarray(m_eager.compute()))

    def test_concurrent_updates_during_inflight_compile(self, cache_env):
        """Updates keep landing (eagerly, exactly once each) while the
        worker is busy; after the swap-in the tail of the stream runs
        compiled; the total matches the eager reference."""
        gate_release = time.monotonic() + 0.7
        compile_cache.get_worker().submit(lambda: time.sleep(max(0.0, gate_release - time.monotonic())))
        m_bg = SumMetric(nan_strategy="ignore")
        m_bg.set_background_compile(True)
        m_eager = SumMetric(nan_strategy="ignore")
        m_eager._executor_enabled = False
        batches = [jnp.full((16,), float(i)) for i in range(30)]
        for b in batches:
            m_bg.update(b)
            m_eager.update(b)
        s = executor_stats(m_bg)
        assert s["eager_misses"] >= 1  # at least the stalled-worker window ran eagerly
        compile_cache.drain_worker(90)
        m_bg.update(jnp.ones(16))
        m_eager.update(jnp.ones(16))
        assert executor_stats(m_bg)["calls"] >= 1  # compiled tail engaged
        assert float(m_bg.compute()) == float(m_eager.compute())
        assert m_bg.update_count == m_eager.update_count == len(batches) + 1

    def test_rollback_during_eager_miss_phase(self, cache_env):
        """A transactional failure while the key is still compiling in the
        background rolls back exactly like the pre-executor eager path."""
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.set_background_compile(True)
        batch = _mc_batch(32)
        m.update(*batch)  # eager miss; compile in flight
        pre_count = m.update_count
        pre_value = float(m.compute())
        with faults.raise_in_update(m, after_mutation=True):
            with pytest.raises(faults.FaultInjected):
                m.update(*batch)
        assert m.update_count == pre_count
        assert float(m.compute()) == pre_value
        _swap_in(m, batch)
        m.update(*batch)  # swapped-in executable still serves correctly
        assert executor_stats(m)["calls"] == 1

    def test_recovery_restore_on_swapped_in_executable(self, cache_env):
        """The PR-2/4 donation-recovery machinery applies unchanged to a
        background-compiled executable: a consumed-donation dispatch failure
        restores the state and propagates, without disabling the executor."""
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        ref = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        m.set_background_compile(True)
        batch = _mc_batch(32)
        for _ in range(3):  # eager-miss step, then compiled copy + donation streak
            m.update(*batch)
            ref.update(*batch)
            if executor_stats(m)["calls"] == 0:
                _swap_in(m, batch)
        assert executor_stats(m)["donated_calls"] >= 1  # live buffers are in play
        pre_count = m.update_count
        with faults.fail_dispatch(consume=True):
            with pytest.raises(faults.FaultInjected):
                m.update(*batch)
        s = executor_stats(m)
        assert s["dispatch_failures"] == 1 and s["recovery_restores"] >= 1
        assert s["disabled_reason"] is None
        assert m.update_count == pre_count
        assert float(m.compute()) == float(ref.compute())

    def test_collection_background_swap_in(self, cache_env):
        coll = MetricCollection(
            {
                "f1": MulticlassF1Score(num_classes=NUM_CLASSES, validate_args=False),
                "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
            }
        )
        batch = _mc_batch(64)
        coll.update(*batch)  # first update resolves groups (eager by design)
        coll.set_background_compile(True)
        coll.update(*batch)  # fused key cold -> eager per-group loop serves it
        assert executor_stats(coll)["eager_misses"] >= 1
        compile_cache.drain_worker(90)
        coll.update(*batch)
        assert executor_stats(coll)["calls"] >= 1
        ref = MetricCollection(
            {
                "f1": MulticlassF1Score(num_classes=NUM_CLASSES, validate_args=False),
                "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
            },
            executor=False,
        )
        for _ in range(3):
            ref.update(*batch)
        out, ref_out = coll.compute(), ref.compute()
        for k in ref_out:
            assert np.allclose(np.asarray(out[k]), np.asarray(ref_out[k]))


# ----------------------------------------------------------- cross-process

@pytest.mark.slow
def test_cold_vs_persisted_process(tmp_path):
    """The whole point: a second process's first call must reuse the first
    process's executables (disk_hits > 0) and agree on the value."""
    script = r"""
import os, sys, time, json
import jax, jax.numpy as jnp, numpy as np
from torchmetrics_tpu.classification import MulticlassAccuracy
from torchmetrics_tpu.ops import compile_cache
from torchmetrics_tpu.ops.executor import executor_stats
m = MulticlassAccuracy(num_classes=5, validate_args=False)
r = np.random.RandomState(0)
preds = jnp.asarray(r.randn(32, 5).astype(np.float32)); target = jnp.asarray(r.randint(0, 5, 32))
t0 = time.perf_counter(); m.update(preds, target)
jax.block_until_ready(list(m._state.values()))
dt = time.perf_counter() - t0
compile_cache.drain_worker(120)
s = executor_stats(m)
print(json.dumps({"first_call_s": dt, "disk_hits": s["disk_hits"], "compiles": s["compiles"],
                  "value": float(m.compute())}))
"""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        TORCHMETRICS_TPU_COMPILE_AHEAD="1",
        TORCHMETRICS_TPU_CACHE_DIR=str(tmp_path / "xcache"),
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    runs = []
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", script], capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    cold, persisted = runs
    assert cold["disk_hits"] == 0 and cold["compiles"] == 1
    assert persisted["disk_hits"] == 1 and persisted["compiles"] == 0
    assert persisted["value"] == cold["value"]
