"""CLIPScore / CLIP-IQA tests against the reference formulas with a fake embedder."""
import sys

import numpy as np
import pytest

sys.path.insert(0, "/root/repo/tests")

import torchmetrics_tpu as tm  # noqa: E402
from torchmetrics_tpu.functional.multimodal import (  # noqa: E402
    clip_image_quality_assessment,
    clip_score,
)

rng = np.random.RandomState(3)
DIM = 16


def _img_embed(images):
    # deterministic pseudo-embedding from channel statistics
    images = np.asarray(images)
    feats = np.stack(
        [images.mean(axis=(1, 2, 3)) * (k + 1) + np.sin(images.std(axis=(1, 2, 3)) + k) for k in range(DIM)],
        axis=1,
    )
    return feats


def _txt_embed(texts):
    out = []
    for t in texts:
        h = np.frombuffer(str(t).encode() * DIM, dtype=np.uint8)[: DIM * 4].astype(np.float64)
        out.append(np.sin(h.reshape(DIM, 4).sum(1)))
    return np.stack(out)


def _joint_embed(images, texts):
    return _img_embed(images), _txt_embed(texts)


IMAGES = rng.rand(4, 3, 8, 8).astype(np.float32)
TEXTS = ["a cat", "a dog", "a house", "a tree"]


def _expected_clip_score(images, texts):
    i = _img_embed(images)
    t = _txt_embed(texts)
    i = i / np.linalg.norm(i, axis=-1, keepdims=True)
    t = t / np.linalg.norm(t, axis=-1, keepdims=True)
    return max(0.0, float((100 * (i * t).sum(-1)).mean()))


def test_clip_score_functional():
    got = float(clip_score(IMAGES, TEXTS, _joint_embed))
    np.testing.assert_allclose(got, _expected_clip_score(IMAGES, TEXTS), rtol=1e-5)


def test_clip_score_modular_accumulation():
    m = tm.CLIPScore(embedding_fn=_joint_embed)
    m.update(IMAGES[:2], TEXTS[:2])
    m.update(IMAGES[2:], TEXTS[2:])
    np.testing.assert_allclose(float(m.compute()), _expected_clip_score(IMAGES, TEXTS), rtol=1e-5)


def test_clip_score_validation():
    with pytest.raises(ModuleNotFoundError):
        tm.CLIPScore()
    m = tm.CLIPScore(embedding_fn=_joint_embed)
    with pytest.raises(ValueError, match="same"):
        m.update(IMAGES, TEXTS[:2])
    with pytest.raises(ValueError, match="3d"):
        m.update([IMAGES[0][None]], [TEXTS[0]])


def test_clip_iqa_functional_single_prompt():
    probs = clip_image_quality_assessment(IMAGES, _img_embed, _txt_embed, prompts=("quality",))
    # manual formula
    i = _img_embed(IMAGES)
    i = i / np.linalg.norm(i, axis=-1, keepdims=True)
    a = _txt_embed(["Good photo.", "Bad photo."])
    a = a / np.linalg.norm(a, axis=-1, keepdims=True)
    logits = 100 * i @ a.T
    e = np.exp(logits - logits.max(-1, keepdims=True))
    want = (e / e.sum(-1, keepdims=True))[:, 0]
    np.testing.assert_allclose(np.asarray(probs), want, rtol=1e-4)


def test_clip_iqa_multi_prompt_and_modular():
    prompts = ("quality", ("Warm photo.", "Cold photo."))
    probs = clip_image_quality_assessment(IMAGES, _img_embed, _txt_embed, prompts=prompts)
    assert set(probs.keys()) == {"quality", "user_defined_0"}
    m = tm.CLIPImageQualityAssessment(_img_embed, _txt_embed, prompts=prompts)
    m.update(IMAGES[:2])
    m.update(IMAGES[2:])
    res = m.compute()
    # reference semantics: per-image scores, concatenated across updates
    np.testing.assert_allclose(np.asarray(res["quality"]), np.asarray(probs["quality"]), rtol=1e-5)


def test_clip_iqa_validation():
    with pytest.raises(ValueError, match="prompts"):
        clip_image_quality_assessment(IMAGES, _img_embed, _txt_embed, prompts=("not_a_prompt",))
    with pytest.raises(ValueError, match="length 2"):
        clip_image_quality_assessment(IMAGES, _img_embed, _txt_embed, prompts=(("a", "b", "c"),))
    with pytest.raises(ModuleNotFoundError):
        tm.CLIPImageQualityAssessment()
