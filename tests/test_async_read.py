"""Fully asynchronous read path (ISSUE 9): ``compute_async``/``sync_async``.

Covers the acceptance properties:

- ``compute_async().result()`` bit-exact vs blocking ``compute()`` for all
  five state families (sum/mean/max/min/cat) in step AND deferred modes,
  for collections, and for laned metrics including quarantined lanes;
- snapshot isolation: mutating the metric (update/reset/load_state) before
  the future resolves never changes what the future serves, and the live
  deferred flags stay coherent;
- failure contracts: ``on_sync_failure`` policies inside an in-flight future
  (raise -> future error; local -> local value; last_good -> DegradedValue),
  sync timeouts, and no wedged worker afterwards;
- chaos composition (testing/faults.py): preemption flush with a read in
  flight, kill/restore while a future is pending;
- the Autosaver ride-along and the reads.* telemetry.
"""
import os
import signal

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MetricCollection,
    MinMetric,
    SumMetric,
    drain_async_reads,
    obs,
    pending_reads,
)
from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassConfusionMatrix
from torchmetrics_tpu.io import Autosaver, restore_state, save_state
from torchmetrics_tpu.io.checkpoint import install_preemption_handler
from torchmetrics_tpu.lanes import LanedCollection, LanedMetric
from torchmetrics_tpu.ops.async_read import MetricFuture, ReadPipeline, get_pipeline
from torchmetrics_tpu.quarantine import DegradedValue
from torchmetrics_tpu.testing import faults
from torchmetrics_tpu.utils.exceptions import SyncTimeoutError

TIMEOUT = 30.0


def _vals_equal(a, b):
    la = jnp.asarray(a) if not isinstance(a, (list, tuple, dict)) else a
    if isinstance(a, dict):
        assert sorted(a) == sorted(b)
        for k in a:
            _vals_equal(a[k], b[k])
        return
    np.testing.assert_array_equal(np.asarray(la), np.asarray(b))


FAMILIES = [
    (SumMetric, [2.0, -1.5, 3.25]),
    (MeanMetric, [2.0, 5.0, 6.5]),
    (MaxMetric, [1.0, 9.0, -2.0]),
    (MinMetric, [4.0, -3.0, 7.0]),
    (CatMetric, [1.0, 2.0, 3.0]),
]


class TestExactness:
    @pytest.mark.parametrize("cls,vals", FAMILIES, ids=lambda p: getattr(p, "__name__", ""))
    @pytest.mark.parametrize("reduce", ["step", "deferred"])
    def test_family_bit_exact(self, cls, vals, reduce):
        m = cls(reduce=reduce)
        ref = cls(reduce=reduce)
        for v in vals:
            batch = jnp.asarray([v, v + 0.5])
            m.update(batch)
            ref.update(batch)
        fut = m.compute_async()
        blocking = ref.compute()
        _vals_equal(fut.result(TIMEOUT), blocking)
        assert fut.done() and fut.exception() is None

    def test_classification_metric(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(32, 5).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 5, 32))
        m = MulticlassAccuracy(num_classes=5)
        m.update(logits, target)
        fut = m.compute_async()
        _vals_equal(fut.result(TIMEOUT), m.compute())

    def test_collection_matches_blocking(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(16, 5).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 5, 16))
        coll = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=5), "cm": MulticlassConfusionMatrix(num_classes=5)}
        )
        coll.update(logits, target)
        fut = coll.compute_async()
        res = fut.result(TIMEOUT)
        blocking = coll.compute()
        assert sorted(res) == sorted(blocking)
        for k in blocking:
            _vals_equal(res[k], blocking[k])

    def test_executor_donation_interplay(self):
        """An in-flight read's snapshot survives the next donating dispatch:
        the escape flag forces copy-before-donate (the double buffer)."""
        m = SumMetric()  # executor on by default
        with faults.pause_async_reads():
            m.update(jnp.asarray([1.0, 2.0]))
            fut = m.compute_async()
            for _ in range(5):  # donating dispatches while the read is parked
                m.update(jnp.asarray([10.0, 10.0]))
        assert float(fut.result(TIMEOUT)) == 3.0
        assert float(m.compute()) == 103.0

    def test_value_is_ready(self):
        m = SumMetric()
        m.update(jnp.asarray([1.0]))
        v = m.compute_async().result(TIMEOUT)
        # resolved values are block_until_ready'd: float() is a memcpy
        assert float(v) == 1.0


class TestFutureSemantics:
    def test_snapshot_isolation_and_flag_coherence(self):
        m = SumMetric(reduce="deferred")
        m.update(jnp.asarray([1.0]))
        with faults.pause_async_reads():
            fut = m.compute_async()
            m.update(jnp.asarray([5.0]))
            # live deferred flags reflect the LIVE accumulation, untouched by
            # the in-flight read
            assert m.deferred_pending
            assert not fut.done()
        assert float(fut.result(TIMEOUT)) == 1.0
        assert float(m.compute()) == 6.0

    def test_reset_before_resolve(self):
        m = SumMetric()
        m.update(jnp.asarray([7.0]))
        with faults.pause_async_reads():
            fut = m.compute_async()
            m.reset()
        assert float(fut.result(TIMEOUT)) == 7.0
        assert int(m.update_count) == 0

    def test_cache_writeback_only_when_unchanged(self):
        m = SumMetric()
        m.update(jnp.asarray([2.0]))
        fut = m.compute_async()
        fut.result(TIMEOUT)
        drain_async_reads()
        assert m.__dict__.get("_computed") is not None  # refreshed: no update since
        m2 = SumMetric()
        m2.update(jnp.asarray([2.0]))
        with faults.pause_async_reads():
            fut2 = m2.compute_async()
            m2.update(jnp.asarray([1.0]))
        fut2.result(TIMEOUT)
        drain_async_reads()
        assert m2.__dict__.get("_computed") is None  # stale read must not cache
        assert float(m2.compute()) == 3.0

    def test_done_callback(self):
        m = SumMetric()
        m.update(jnp.asarray([1.0]))
        seen = []
        fut = m.compute_async()
        fut.result(TIMEOUT)
        fut.add_done_callback(lambda f: seen.append(float(f.result())))
        assert seen == [1.0]

    def test_result_timeout(self):
        with faults.pause_async_reads():
            m = SumMetric()
            m.update(jnp.asarray([1.0]))
            fut = m.compute_async()
            with pytest.raises(TimeoutError):
                fut.result(0.05)
        assert float(fut.result(TIMEOUT)) == 1.0

    def test_repeated_reads_chain(self):
        m = SumMetric()
        futures = []
        for i in range(5):
            m.update(jnp.asarray([float(i)]))
            futures.append(m.compute_async())
        expected = np.cumsum(np.arange(5.0))
        for fut, want in zip(futures, expected):
            assert float(fut.result(TIMEOUT)) == want

    def test_wrapper_metrics_resolve_inline(self):
        from torchmetrics_tpu.wrappers import MinMaxMetric

        w = MinMaxMetric(MeanMetric())
        w.update(jnp.asarray([3.0]))
        res = w.compute_async().result(TIMEOUT)
        blocking = w.compute()
        for k in blocking:
            _vals_equal(res[k], blocking[k])

    def test_sync_async_returns_state(self):
        m = SumMetric()
        m.update(jnp.asarray([4.0]))
        st = m.sync_async().result(TIMEOUT)
        assert float(st["sum_value"]) == 4.0
        assert int(st["_update_count"]) == 1
        # live metric untouched: no _is_synced latch
        assert not m._is_synced


def _dist_metric(**kwargs):
    return SumMetric(
        nan_strategy="ignore", executor=False, distributed_available_fn=lambda: True, **kwargs
    )


class TestSyncFailurePolicies:
    def test_break_sync_raise_policy(self):
        m = _dist_metric(on_sync_failure="raise")
        m.update(jnp.asarray([1.0]))
        with faults.break_sync():
            fut = m.compute_async()
            err = fut.exception(TIMEOUT)  # waits inside the armed context
        assert isinstance(err, faults.FaultInjected)
        with pytest.raises(faults.FaultInjected):
            fut.result(TIMEOUT)
        # worker not wedged: the next read resolves fine (sync healthy again)
        fut2 = m.compute_async()
        assert float(fut2.result(TIMEOUT)) == 1.0

    def test_break_sync_local_policy(self):
        m = _dist_metric(on_sync_failure="local")
        m.update(jnp.asarray([2.0]))
        with faults.break_sync():
            fut = m.compute_async()
            assert float(fut.result(TIMEOUT)) == 2.0
        drain_async_reads()
        assert m.last_sync_ok is False  # degradation visible on the live metric

    def test_break_sync_last_good_policy(self):
        m = _dist_metric(on_sync_failure="last_good")
        m.update(jnp.asarray([3.0]))
        assert float(m.compute()) == 3.0  # seeds the last-good cache
        m.update(jnp.asarray([1.0]))
        with faults.break_sync():
            fut = m.compute_async()
            res = fut.result(TIMEOUT)
        assert isinstance(res, DegradedValue)
        assert float(res.value) == 3.0
        assert res.updates_behind == 1
        assert fut.degraded

    def test_hang_sync_timeout(self):
        m = _dist_metric(sync_timeout=0.2, on_sync_failure="raise")
        m.update(jnp.asarray([1.0]))
        with faults.hang_sync(seconds=5.0):
            fut = m.compute_async()
            err = fut.exception(TIMEOUT)
        assert isinstance(err, SyncTimeoutError)
        # the pipeline worker survived the timed-out gather
        fut2 = m.compute_async()
        assert float(fut2.result(TIMEOUT)) == 1.0


class TestLanedReads:
    def test_laned_aggregate_exact(self):
        lm = LanedMetric(SumMetric(), capacity=8)
        lm.update_sessions([("a", jnp.asarray([1.0, 2.0])), ("b", jnp.asarray([4.0, 0.5]))])
        fut = lm.compute_async()
        _vals_equal(fut.result(TIMEOUT), lm.compute())

    def test_laned_quarantined_lanes_excluded(self):
        lq = LanedMetric(SumMetric(), capacity=8, on_lane_fault="quarantine")
        lq.update_sessions([("good", jnp.asarray([1.0])), ("bad", jnp.asarray([2.0]))])
        assert float(lq.compute()) == 3.0  # seeds last-good for everyone
        lq.update_sessions([("good", jnp.asarray([1.0])), ("bad", jnp.asarray([np.nan]))])
        fut = lq.compute_async()
        v_async = fut.result(TIMEOUT)
        # the async scan quarantined 'bad' on the LIVE guard
        assert "bad" in lq.guard.quarantined
        v_block = lq.compute()
        _vals_equal(v_async, v_block)
        assert float(v_async) == 2.0  # good's lane only
        degraded = lq.compute_session("bad")
        assert isinstance(degraded, DegradedValue)

    def test_laned_eager_mode_inline(self):
        lm = LanedMetric(CatMetric(), capacity=8)  # list state -> eager lanes
        lm.update_sessions([("a", jnp.asarray([1.0, 2.0]))])
        fut = lm.compute_async()
        _vals_equal(fut.result(TIMEOUT), lm.compute())

    def test_laned_collection(self):
        lc = LanedCollection({"s": SumMetric(), "m": MaxMetric()}, capacity=8)
        lc.update_sessions([("a", jnp.asarray([1.0, 2.0])), ("b", jnp.asarray([5.0, 1.0]))])
        fut = lc.compute_async()
        res = fut.result(TIMEOUT)
        blocking = lc.compute()
        assert sorted(res) == sorted(blocking)
        for k in blocking:
            _vals_equal(res[k], blocking[k])

    def test_laned_update_while_read_in_flight(self):
        lm = LanedMetric(SumMetric(), capacity=8, on_lane_fault="quarantine")
        lm.update_sessions([("a", jnp.asarray([1.0])), ("b", jnp.asarray([2.0]))])
        with faults.pause_async_reads():
            fut = lm.compute_async()
            lm.update_sessions([("a", jnp.asarray([10.0])), ("b", jnp.asarray([20.0]))])
        assert float(fut.result(TIMEOUT)) == 3.0
        assert float(lm.compute()) == 33.0


class TestChaosComposition:
    def test_preemption_flush_with_read_in_flight(self, tmp_path):
        """SIGTERM lands while a read is parked in the pipeline: the flush
        saves the live state, the handler chains, and the future still
        resolves to its submission-time value afterwards."""
        m = SumMetric(executor=False)
        m.update(jnp.asarray([5.0]))
        saver = Autosaver(m, str(tmp_path / "ckpt"), every_n_updates=1000)
        chained = []
        previous = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
        try:
            handle = install_preemption_handler(saver, signums=(signal.SIGTERM,))
            try:
                with faults.pause_async_reads():
                    fut = m.compute_async()
                    m.update(jnp.asarray([2.0]))
                    os.kill(os.getpid(), signal.SIGTERM)
                assert chained == [signal.SIGTERM]
            finally:
                handle.uninstall()
        finally:
            signal.signal(signal.SIGTERM, previous)
        assert float(fut.result(TIMEOUT)) == 5.0
        fresh = SumMetric(executor=False)
        restore_state(str(tmp_path / "ckpt"), fresh)
        assert float(fresh.compute()) == 7.0  # the flush saved the LIVE state

    def test_kill_restore_while_future_pending(self, tmp_path):
        m = SumMetric(executor=False)
        m.update(jnp.asarray([3.0]))
        save_state(m, str(tmp_path / "ckpt"))
        with faults.pause_async_reads():
            fut = m.compute_async()
            # "kill": a fresh process restores from the snapshot while the old
            # future is still pending
            fresh = SumMetric(executor=False)
            restore_state(str(tmp_path / "ckpt"), fresh)
            # and the SAME instance can also be overwritten mid-flight
            m.load_state(fresh.state())
        assert float(fut.result(TIMEOUT)) == 3.0
        assert float(fresh.compute()) == 3.0
        assert float(m.compute()) == 3.0

    def test_no_wedged_worker_with_abandoned_future(self):
        """A future nobody waits on must not wedge anything: the barrier
        (bounded) releases, the pipeline drains, and the worker thread is a
        daemon so interpreter exit can never block on it."""
        m = SumMetric()
        m.update(jnp.asarray([1.0]))
        with faults.pause_async_reads(max_s=0.2):
            m.compute_async()  # abandoned on purpose
        assert drain_async_reads(timeout=TIMEOUT)
        pipeline = get_pipeline()
        assert pipeline._thread is not None and pipeline._thread.daemon


class TestPipeline:
    def test_inline_fallback_on_full_queue(self):
        import threading

        pipeline = ReadPipeline(maxsize=1)
        release = threading.Event()
        pipeline.submit(lambda: release.wait(10.0), owner="barrier")  # occupies the worker
        pipeline.submit(lambda: 1, owner="queued")  # fills the queue
        fut = pipeline.submit(lambda: 42, owner="overflow")  # runs inline
        assert fut.done() and fut.result() == 42
        assert pipeline.stats["inline"] == 1
        release.set()
        assert pipeline.drain(TIMEOUT)

    def test_pending_gauge_and_counters(self):
        before = obs.counters_snapshot()
        m = SumMetric()
        m.update(jnp.asarray([1.0]))
        fut = m.compute_async()
        fut.result(TIMEOUT)
        drain_async_reads()
        after = obs.counters_snapshot()
        assert after.get("reads.async_submitted", 0) > before.get("reads.async_submitted", 0)
        assert after.get("reads.async_completed", 0) > before.get("reads.async_completed", 0)
        assert pending_reads() == 0

    def test_degraded_counter(self):
        before = obs.counters_snapshot().get("reads.async_degraded", 0)
        m = _dist_metric(on_sync_failure="last_good")
        m.update(jnp.asarray([1.0]))
        m.compute()
        m.update(jnp.asarray([1.0]))
        with faults.break_sync():
            m.compute_async().result(TIMEOUT)
        drain_async_reads()
        assert obs.counters_snapshot().get("reads.async_degraded", 0) == before + 1

    def test_compute_async_span(self):
        obs.reset_ring()
        obs.set_tracing(True)
        try:
            m = SumMetric()
            m.update(jnp.asarray([1.0]))
            m.compute_async().result(TIMEOUT)
        finally:
            obs.set_tracing(None)
        names = {ev.name for ev in obs.peek_events()}
        assert any(n.startswith("tm_tpu.compute_async") for n in names)


class TestAutosaverRideAlong:
    def test_background_save_rides_pipeline(self, tmp_path):
        m = SumMetric(executor=False)
        saver = Autosaver(
            m, str(tmp_path / "ckpt"), every_n_updates=1, background=True, reuse_recovery=False
        ).attach()
        try:
            m.update(jnp.asarray([4.0]))
            saver.flush(TIMEOUT)
        finally:
            saver.detach()
        assert saver.stats["async_rides"] >= 1
        assert saver.stats["saves"] >= 1
        fresh = SumMetric(executor=False)
        restore_state(str(tmp_path / "ckpt"), fresh)
        assert float(fresh.compute()) == 4.0

    def test_ride_along_snapshot_is_consistent(self, tmp_path):
        """The staged references are immutable: updates landing after the
        stage (but before the worker's D2H) never leak into the snapshot."""
        m = SumMetric(executor=False)
        saver = Autosaver(
            m, str(tmp_path / "ckpt"), every_n_updates=1000, background=True, reuse_recovery=False
        )
        m.update(jnp.asarray([1.0]))
        with faults.pause_async_reads():
            saver.save_now()
            m.update(jnp.asarray([100.0]))
        saver.flush(TIMEOUT)
        fresh = SumMetric(executor=False)
        restore_state(str(tmp_path / "ckpt"), fresh)
        assert float(fresh.compute()) == 1.0

    def test_recovery_reuse_still_wins(self, tmp_path):
        """With a fresh executor recovery snapshot available, the Autosaver
        keeps the zero-copy reuse path (no pipeline ride needed)."""
        from torchmetrics_tpu import Metric

        class _SumLike(Metric):  # executor-eligible (aggregators self-declare untraceable)
            full_state_update = False

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, x):
                self.total = self.total + x.sum()

            def compute(self):
                return self.total

        m = _SumLike()
        for _ in range(3):
            m.update(jnp.asarray([1.0, 2.0]))  # warm the executor into donation
        assert m.executor_status["stats"]["donated_calls"] >= 1
        saver = Autosaver(m, str(tmp_path / "ckpt"), every_n_updates=2, background=True).attach()
        try:
            m.update(jnp.asarray([1.0, 2.0]))
            m.update(jnp.asarray([1.0, 2.0]))  # trigger: recovery is fresh
            saver.flush(TIMEOUT)
        finally:
            saver.detach()
        assert saver.stats["reused_recovery_snapshots"] >= 1
        assert saver.stats["async_rides"] == 0  # zero-copy reuse beat the ride
