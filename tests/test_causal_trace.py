"""Causal tracing + flight recorder + histogram suite (ISSUE 13).

Three acceptance surfaces:

- **Cross-thread trace integrity**: a ``compute_async()`` under tracing
  produces a caller-half span and a worker-replay span sharing ONE
  ``trace_id``, connected by a valid Perfetto flow-event pair (``ph:"s"``
  bound inside the submitting slice on the submitting thread, ``ph:"f"`` at
  the worker span with a matching ``id``) — proven for all four async
  domains: async read, background compile, autosave, shard-shadow refresh.
- **Fault flight recorder**: every typed fault injected via
  ``testing/faults.py`` (ShardLossError, LaneFaultError, SyncTimeoutError,
  StateCorruptionError/CheckpointCorruptionError, DispatchStallError) leaves
  a breadcrumb whose ``flight`` blob carries the faulting window's spans and
  counter deltas; the watchdog's fatal path persists the recorder to disk.
- **Histogram instruments**: async read end-to-end latency + queue wait,
  dispatch duration, and DegradedValue staleness-age land in fixed-bucket
  registry histograms exposed in valid Prometheus histogram exposition
  (``_bucket``/``_sum``/``_count`` with ``# HELP``/``# TYPE`` on every
  series — the strict-scraper satellite).

Runs on the 8-fake-device CPU mesh from conftest.py.
"""
import gc
import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, "/root/repo/tests")

import torchmetrics_tpu as tm  # noqa: E402
from torchmetrics_tpu import Metric, MetricCollection, obs  # noqa: E402
from torchmetrics_tpu.aggregation import SumMetric  # noqa: E402
from torchmetrics_tpu.classification import (  # noqa: E402
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
)
from torchmetrics_tpu.io import restore_state, save_state  # noqa: E402
from torchmetrics_tpu.lanes import LanedMetric  # noqa: E402
from torchmetrics_tpu.ops import compile_cache  # noqa: E402
from torchmetrics_tpu.ops.async_read import drain_pipeline  # noqa: E402
from torchmetrics_tpu.ops.executor import make_deferred_collection_step  # noqa: E402
from torchmetrics_tpu.quarantine import DegradedValue  # noqa: E402
from torchmetrics_tpu.testing import faults  # noqa: E402
from torchmetrics_tpu.utils.exceptions import (  # noqa: E402
    CheckpointCorruptionError,
    DispatchStallError,
    ShardLossError,
    SyncTimeoutError,
)

NUM_DEVICES = 8
NUM_CLASSES = 5
BATCH = 64


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Fresh telemetry state per test: tracing ON, registry/ring/flight
    zeroed; env-default flags restored afterwards."""
    obs.set_telemetry(True)
    obs.set_tracing(True)
    obs.set_flight(True)
    obs.reset()
    obs.reset_ring()
    obs.reset_flight()
    yield
    drain_pipeline(30.0)
    obs.reset()
    obs.reset_ring()
    obs.reset_flight()
    obs.set_flight(None)
    obs.set_tracing(None)
    obs.set_telemetry(None)


def _batch(n=BATCH, seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(n, NUM_CLASSES).astype(np.float32)),
        jnp.asarray(rng.randint(0, NUM_CLASSES, n)),
    )


def _mesh(d=NUM_DEVICES):
    return Mesh(np.array(jax.devices()[:d]), ("batch",))


def _put(mesh, arr, spec=P("batch")):
    return jax.device_put(arr, NamedSharding(mesh, spec))


class _SumLike(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + x.sum()

    def compute(self):
        return self.total


def _assert_linked(events, caller_name, worker_name):
    """The cross-thread acceptance: the worker-side span shares the caller
    span's trace_id, is parented under it, and carries the flow source that
    the exporter turns into the s/f pair. Returns (caller, worker) events."""
    callers = [e for e in events if e.name.startswith(caller_name)]
    workers = [e for e in events if e.name.startswith(worker_name)]
    assert callers, f"no caller span {caller_name} in {sorted({e.name for e in events})}"
    assert workers, f"no worker span {worker_name} in {sorted({e.name for e in events})}"
    caller = callers[-1]
    linked = [w for w in workers if w.trace_id == caller.trace_id]
    assert linked, (
        f"no {worker_name} span shares trace_id {caller.trace_id}"
        f" (worker trace ids: {[w.trace_id for w in workers]})"
    )
    worker = linked[-1]
    assert worker.trace_id == caller.trace_id != 0
    return caller, worker


def _assert_flow_pair(doc, caller, worker):
    """The Perfetto contract: one s/f pair with a shared id, the start bound
    inside the submitting slice on the submitting thread, the finish at the
    worker slice's start on the worker thread."""
    flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "f")]
    starts = {e["id"]: e for e in flows if e["ph"] == "s"}
    finishes = {e["id"]: e for e in flows if e["ph"] == "f"}
    matching = [
        fid for fid in starts
        if fid in finishes and starts[fid]["args"].get("trace_id") == caller.trace_id
    ]
    assert matching, f"no flow pair for trace {caller.trace_id}"
    fid = matching[-1]
    s, f = starts[fid], finishes[fid]
    assert s["tid"] == caller.tid and f["tid"] == worker.tid
    assert caller.t_start_ns / 1e3 <= s["ts"] <= caller.t_end_ns / 1e3, (
        "flow start must bind inside the submitting slice"
    )
    assert f["ts"] == pytest.approx(worker.t_start_ns / 1e3)
    assert f.get("bp") == "e"


# ---------------------------------------------------------------------------
# trace-context unit semantics
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_nested_spans_share_trace_and_chain_parents(self):
        with obs.span("outer"):
            with obs.span("inner"):
                pass
        inner, outer = obs.drain_events()
        assert inner.trace_id == outer.trace_id != 0
        assert inner.parent_id == outer.span_id
        assert outer.parent_id == 0

    def test_separate_roots_get_separate_traces(self):
        with obs.span("a"):
            pass
        with obs.span("b"):
            pass
        a, b = obs.drain_events()
        assert a.trace_id != b.trace_id

    def test_capture_and_reopen_across_threads(self):
        with obs.span("submit") as _:
            ctx = obs.capture_context()

        def worker():
            with obs.use_context(ctx):
                with obs.span("replay"):
                    with obs.span("nested"):
                        pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        by_name = {e.name: e for e in obs.drain_events()}
        submit, replay, nested = by_name["submit"], by_name["replay"], by_name["nested"]
        assert replay.trace_id == nested.trace_id == submit.trace_id
        assert replay.parent_id == submit.span_id
        # the flow source lands on the FIRST reopened span only
        assert replay.flow_src == (submit.span_id, submit.tid, ctx.t_ns)
        assert nested.flow_src is None

    def test_capture_returns_none_when_tracing_off(self):
        obs.set_tracing(False)
        assert obs.capture_context() is None
        with obs.use_context(None):  # the no-op carry
            with obs.span("x"):
                pass
        assert obs.peek_events() == []

    def test_context_restores_on_exit(self):
        with obs.span("submit"):
            ctx = obs.capture_context()
        with obs.span("outer"):
            before = obs.current_trace_id()
            with obs.use_context(ctx):
                assert obs.current_trace_id() == ctx.trace_id
            assert obs.current_trace_id() == before


# ---------------------------------------------------------------------------
# cross-thread integrity: the four async domains
# ---------------------------------------------------------------------------


class TestFourDomains:
    def test_async_read_domain(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        fut = m.compute_async()
        fut.result(60.0)
        drain_pipeline(30.0)
        events = obs.peek_events()
        caller, worker = _assert_linked(events, "tm_tpu.compute_async", "tm_tpu.read.resolve")
        assert worker.tid != caller.tid, "worker replay must run off the submitting thread"
        _assert_flow_pair(obs.chrome_trace(), caller, worker)

    def test_background_compile_domain(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "1")
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(tmp_path))
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.set_background_compile(True)
        m.update(*_batch())
        assert compile_cache.drain_worker(timeout=60.0)
        events = obs.peek_events()
        enqueues = [
            e for e in events
            if e.name == obs.SPAN_COMPILE and (e.attrs or {}).get("phase") == "enqueue"
        ]
        compiles = [
            e for e in events
            if e.name == obs.SPAN_COMPILE and (e.attrs or {}).get("background")
        ]
        assert enqueues and compiles
        caller, worker = enqueues[-1], compiles[-1]
        assert worker.trace_id == caller.trace_id != 0
        assert worker.tid != caller.tid
        _assert_flow_pair(obs.chrome_trace(), caller, worker)

    def test_autosave_domain(self, tmp_path):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        saver = tm.Autosaver(m, str(tmp_path / "ckpt"), every_n_updates=1).attach()
        try:
            m.update(*_batch())
            saver.flush(30.0)
        finally:
            saver.detach()
        drain_pipeline(30.0)
        events = obs.peek_events()
        caller, worker = _assert_linked(events, "tm_tpu.autosave", "tm_tpu.checkpoint.save")
        assert worker.tid != caller.tid
        _assert_flow_pair(obs.chrome_trace(), caller, worker)

    def test_shadow_refresh_domain(self):
        coll = MetricCollection({"m": _SumLike(executor=False)}, compute_groups=False)
        step = make_deferred_collection_step(coll, _mesh(), axis_name="batch")
        step.attach_shadow(every_n_steps=1, on_shard_loss="degraded")
        mesh = _mesh()
        st = step.init_states()
        rng = np.random.RandomState(7)
        st = step.local_step(st, _put(mesh, jnp.asarray(rng.randn(8).astype(np.float32))))
        assert drain_pipeline(30.0)
        events = obs.peek_events()
        submits = [
            e for e in events
            if e.name == obs.SPAN_SHADOW and (e.attrs or {}).get("phase") == "submit"
        ]
        refreshes = [
            e for e in events
            if e.name == obs.SPAN_SHADOW and (e.attrs or {}).get("phase") == "refresh"
        ]
        assert submits and refreshes
        caller, worker = submits[-1], refreshes[-1]
        assert worker.trace_id == caller.trace_id != 0
        assert worker.tid != caller.tid
        # the pipeline's resolve span is the flow target; the refresh span
        # nests under it with the same trace
        resolve = [e for e in events if e.name.startswith("tm_tpu.read.resolve/ShardShadow")]
        assert resolve and resolve[-1].trace_id == caller.trace_id
        _assert_flow_pair(obs.chrome_trace(), caller, resolve[-1])

    def test_reduce_async_carries_trace(self):
        coll = MetricCollection({"m": _SumLike(executor=False)}, compute_groups=False)
        step = make_deferred_collection_step(coll, _mesh(), axis_name="batch")
        mesh = _mesh()
        st = step.local_step(step.init_states(), _put(mesh, jnp.ones(8, jnp.float32)))
        fut = step.reduce_async(st)
        fut.result(60.0)
        drain_pipeline(30.0)
        caller, worker = _assert_linked(
            obs.peek_events(), "tm_tpu.compute_async/DeferredCollectionStep", "tm_tpu.read.resolve"
        )
        _assert_flow_pair(obs.chrome_trace(), caller, worker)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_always_on_without_tracing(self):
        """The whole point: flight records exist with the span ring OFF."""
        obs.set_tracing(False)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        assert obs.peek_events() == []  # ring untouched
        snap = obs.flight_snapshot()
        assert snap.get("dispatch"), f"no dispatch flight records: {list(snap)}"
        names = [r["name"] for r in snap["dispatch"]]
        assert any(n.startswith("tm_tpu.dispatch/") for n in names)

    def test_kernel_gate_decisions_ride_the_kernels_domain(self):
        m = MulticlassConfusionMatrix(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        snap = obs.flight_snapshot()
        assert snap.get("kernels"), f"no kernel gate records: {list(snap)}"
        assert any("path=" in r["name"] for r in snap["kernels"])

    def test_newest_wins_bound(self):
        obs.reset_flight(capacity=4)
        for i in range(10):
            obs.flight_note("checkpoint", f"rec{i}")
        snap = obs.flight_snapshot()["checkpoint"]
        assert [r["name"] for r in snap] == ["rec6", "rec7", "rec8", "rec9"]

    def test_blob_carries_counter_deltas_per_window(self):
        obs.flight_blob()  # anchor the window
        obs.counter_inc("test.window_counter", 3)
        blob = obs.flight_blob("dispatch")
        assert blob["counters_delta"].get("test.window_counter") == 3
        # the next window starts empty
        assert "test.window_counter" not in obs.flight_blob("dispatch")["counters_delta"]

    def test_set_flight_off_stops_recording(self):
        obs.set_flight(False)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        assert not obs.flight_snapshot().get("dispatch")

    def test_dump_diagnostics_surfaces_flight(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        d = obs.dump_diagnostics()
        assert "flight" in d and d["flight"].get("dispatch")

    def test_persist_flight_writes_durable_json(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHMETRICS_TPU_FLIGHT_DIR", str(tmp_path))
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        path = obs.persist_flight()
        assert path and os.path.dirname(path) == str(tmp_path)
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["flight"].get("dispatch") and "counters" in doc


# ---------------------------------------------------------------------------
# flight blobs on every typed fault
# ---------------------------------------------------------------------------


def _last_crumb(kind):
    crumbs = [c for c in obs.dump_diagnostics()["breadcrumbs"] if c["kind"] == kind]
    assert crumbs, f"no {kind!r} breadcrumb recorded"
    return crumbs[-1]


def _assert_flight_blob(crumb):
    blob = crumb["data"].get("flight")
    assert blob is not None, f"breadcrumb {crumb['kind']!r} carries no flight blob"
    events = blob["events"]
    flat = [r for rs in (events.values() if isinstance(events, dict) else [events]) for r in rs]
    assert flat, "flight blob holds no spans from the faulting window"
    assert isinstance(blob["counters_delta"], dict)
    return blob


class TestFlightOnTypedFaults:
    def test_shard_loss_error(self):
        coll = MetricCollection({"m": _SumLike(executor=False)}, compute_groups=False)
        step = make_deferred_collection_step(coll, _mesh(), axis_name="batch")
        mesh = _mesh()
        st = step.local_step(step.init_states(), _put(mesh, jnp.ones(8, jnp.float32)))
        with faults.drop_shard(step, shard=3):
            with pytest.raises(ShardLossError):
                step.reduce(st)
        crumb = _last_crumb("shard_loss")
        blob = _assert_flight_blob(crumb)
        assert blob["domain"] == "shadow"
        assert crumb["data"]["shard"] == 3

    def test_lane_fault_error(self):
        laned = LanedMetric(_SumLike(), capacity=8, on_lane_fault="quarantine")
        base = [("a", np.asarray([1.0])), ("b", np.asarray([2.0]))]
        laned.update_sessions(base)
        with faults.poison_session(laned, "a", mode="nan", frac=1.0):
            laned.update_sessions(base)
        laned.lane_values()  # the read point attributes the fault
        crumb = _last_crumb("lane_fault")
        blob = _assert_flight_blob(crumb)
        assert blob["domain"] == "lanes"

    def test_sync_timeout_error(self):
        m = SumMetric(
            nan_strategy="ignore", executor=False,
            distributed_available_fn=lambda: True,
            sync_timeout=0.2, on_sync_failure="raise",
        )
        m.update(jnp.asarray([1.0, 2.0]))
        with faults.hang_sync(seconds=5.0):
            with pytest.raises(SyncTimeoutError):
                m.compute()
        crumb = _last_crumb("sync_timeout")
        _assert_flight_blob(crumb)
        assert crumb["data"]["timeout_s"] == 0.2

    def test_checkpoint_corruption_error(self, tmp_path):
        m = _SumLike()
        m.update(jnp.ones(3))
        path = str(tmp_path / "snap.ckpt")
        save_state(m, path)
        faults.torn_write(path, mode="truncate")
        with pytest.raises(CheckpointCorruptionError):
            restore_state(path, _SumLike())
        _assert_flight_blob(_last_crumb("checkpoint_corruption_error"))

    def test_dispatch_stall_persists_flight_to_disk(self, tmp_path, monkeypatch):
        import time as _time

        monkeypatch.setenv("TORCHMETRICS_TPU_FLIGHT_DIR", str(tmp_path))
        from torchmetrics_tpu.io.retry import stall_watchdog

        # run real dispatches first so the recorder holds the history a
        # post-mortem needs (the stall itself records nothing — it hangs)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        with pytest.raises(DispatchStallError):
            with stall_watchdog(0.1, what="test hang", status=lambda: {"calls": 1}):
                _time.sleep(2.0)
        crumb = _last_crumb("dispatch_stall")
        _assert_flight_blob(crumb)
        assert crumb["data"]["what"] == "test hang"
        dumps = [f for f in os.listdir(tmp_path) if f.startswith("tm_tpu_flight_")]
        assert dumps, "fatal stall must persist the flight recorder to disk"
        with open(tmp_path / dumps[0]) as fh:
            doc = json.load(fh)
        assert "flight" in doc and "breadcrumbs" in doc

    def test_breaker_trip_carries_flight(self):
        laned = LanedMetric(
            _SumLike(), capacity=8, on_lane_fault="quarantine",
            breaker_threshold=2, breaker_window=10,
        )
        base = [("a", np.asarray([1.0])), ("b", np.asarray([2.0]))]
        laned.update_sessions(base)
        with faults.poison_session(laned, "a", mode="nan", frac=1.0):
            for _ in range(3):
                laned.update_sessions(base)
                laned.lane_values()
        _assert_flight_blob(_last_crumb("lane_breaker_trip"))


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


def _parse_prometheus(text):
    """Strict parse: every sample's family must carry # HELP and # TYPE; the
    return maps family -> (kind, [(labels, value)])."""
    helped, typed, samples = set(), {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            helped.add(line.split(" ")[2])
            continue
        if line.startswith("# TYPE "):
            _, _, fam, kind = line.split(" ")
            typed[fam] = kind
            continue
        assert not line.startswith("#"), f"unknown comment line {line!r}"
        name_part, value = line.rsplit(" ", 1)
        if "{" in name_part:
            series, labels = name_part.split("{", 1)
            labels = labels.rstrip("}")
        else:
            series, labels = name_part, ""
        fam = series
        for suffix in ("_bucket", "_sum", "_count"):
            if series.endswith(suffix) and series[: -len(suffix)] in typed:
                fam = series[: -len(suffix)]
        assert fam in typed, f"sample {series!r} has no # TYPE"
        assert fam in helped, f"sample {series!r} has no # HELP"
        samples.setdefault(fam, []).append((series, labels, float(value)))
    return typed, samples


class TestHistograms:
    def test_async_read_latency_and_queue_wait_recorded(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        m.compute_async().result(60.0)
        drain_pipeline(30.0)
        hists = obs.histograms_snapshot()
        assert hists["reads.e2e_latency_us"]["count"] >= 1
        assert hists["reads.queue_wait_us"]["count"] >= 1
        assert hists["reads.e2e_latency_us"]["sum"] > 0

    def test_dispatch_duration_recorded(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        for seed in range(3):
            m.update(*_batch(seed=seed))
        h = obs.histograms_snapshot()["executor.dispatch_us"]
        assert h["count"] >= 3 and sum(h["counts"]) == h["count"]

    def test_staleness_age_recorded_on_degraded_reads(self):
        laned = LanedMetric(_SumLike(), capacity=8, on_lane_fault="quarantine")
        base = [("a", np.asarray([1.0])), ("b", np.asarray([2.0]))]
        laned.update_sessions(base)
        with faults.poison_session(laned, "a", mode="nan", frac=1.0):
            laned.update_sessions(base)
        vals = laned.lane_values()
        assert isinstance(vals["a"], DegradedValue)
        h = obs.histograms_snapshot()["reads.staleness_age_updates"]
        assert h["count"] >= 1

    def test_prometheus_histogram_exposition_is_strict(self):
        obs.counter_inc("checkpoint.saves", 2)
        obs.gauge_set("reads.pending", 1)
        obs.histogram_observe("reads.e2e_latency_us", 900.0)
        obs.histogram_observe("reads.e2e_latency_us", 40_000.0)
        obs.histogram_observe("reads.staleness_age_updates", 3)
        typed, samples = _parse_prometheus(obs.prometheus_text())
        assert typed["tm_tpu_reads_staleness_age_updates"] == "histogram"
        fam = "tm_tpu_reads_e2e_latency_us"
        assert typed[fam] == "histogram"
        buckets = [(lab, v) for series, lab, v in samples[fam] if series.endswith("_bucket")]
        assert buckets[-1][0] == 'le="+Inf"' and buckets[-1][1] == 2
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "bucket counts must be cumulative"
        assert ('le="1000"', 1.0) in buckets
        sums = [v for series, _, v in samples[fam] if series.endswith("_sum")]
        totals = [v for series, _, v in samples[fam] if series.endswith("_count")]
        assert sums == [40_900.0] and totals == [2.0]
        assert typed["tm_tpu_checkpoint_saves_total"] == "counter"
        assert typed["tm_tpu_reads_pending"] == "gauge"

    def test_histogram_off_with_telemetry(self):
        obs.set_telemetry(False)
        obs.histogram_observe("reads.e2e_latency_us", 1.0)
        obs.set_telemetry(True)
        assert "reads.e2e_latency_us" not in obs.histograms_snapshot()

    def test_custom_buckets_validated(self):
        with pytest.raises(ValueError, match="ascending"):
            obs.histogram_observe("bad.hist", 1.0, buckets=(3.0, 1.0))


# ---------------------------------------------------------------------------
# the executor WeakSet leak test (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


class TestSnapshotLeak:
    def test_weakset_releases_a_fleet_of_dead_executors(self):
        """Long-lived serving processes churn metrics: N registered executors
        must all leave the aggregate once garbage-collected, returning
        executor.instances to its baseline (no dead-entry accumulation)."""
        gc.collect()
        baseline = obs.telemetry_snapshot()["counters"].get("executor.instances", 0)
        fleet = []
        for seed in range(6):
            m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
            m.update(*_batch(n=16, seed=seed))
            fleet.append(m)
        during = obs.telemetry_snapshot()["counters"]["executor.instances"]
        assert during >= baseline + 6
        del fleet, m
        gc.collect()
        after = obs.telemetry_snapshot()["counters"].get("executor.instances", 0)
        assert after <= baseline, (
            f"dead executors lingering in the WeakSet: baseline {baseline}, after {after}"
        )
