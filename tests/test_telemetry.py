"""Unified runtime telemetry suite (ISSUE 6).

Covers the obs/ package end to end: snapshot counters agreeing with
``executor_status`` across the eager / fused-collection / deferred /
background-compile paths, ring-buffer wrap semantics (newest events always
survive), Chrome-trace export round-tripping as valid trace-event JSON (the
Perfetto acceptance), span nesting under concurrent background compile +
autosave, zero-cost-when-off, the duration-key standardization (every
duration key carries ``_us``; the one-release ``compile_ms_total`` alias is
gone), the Prometheus exposition format, breadcrumb routing from the fault
paths, and the non-blocking ``observe_ready`` device-timing seam.

Runs on the 8-fake-device CPU mesh from conftest.py.
"""
import json
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, "/root/repo/tests")

import torchmetrics_tpu as tm  # noqa: E402
from torchmetrics_tpu import MetricCollection, obs  # noqa: E402
from torchmetrics_tpu.classification import (  # noqa: E402
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
)
from torchmetrics_tpu.ops import compile_cache  # noqa: E402
from torchmetrics_tpu.ops.executor import make_deferred_collection_step  # noqa: E402

NUM_DEVICES = 8
NUM_CLASSES = 5
BATCH = 64


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Fresh telemetry state per test: tracing ON, registry/ring zeroed;
    env-default flags restored afterwards."""
    obs.set_telemetry(True)
    obs.set_tracing(True)
    obs.reset()
    obs.reset_ring()
    yield
    obs.reset()
    obs.reset_ring()
    obs.set_tracing(None)
    obs.set_telemetry(None)


def _batch(n=BATCH, seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(n, NUM_CLASSES).astype(np.float32)),
        jnp.asarray(rng.randint(0, NUM_CLASSES, n)),
    )


def _span_names():
    return [e.name for e in obs.peek_events()]


# ---------------------------------------------------------------------------
# counters agree with executor_status across execution paths
# ---------------------------------------------------------------------------


class TestSnapshotAgreesWithExecutorStatus:
    def test_eager_executor_path(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        for seed in range(3):
            m.update(*_batch(seed=seed))
        stats = m.executor_status["stats"]
        per_metric = obs.telemetry_snapshot(m)["counters"]
        assert per_metric["executor.calls"] == stats["calls"] == 3
        assert per_metric["executor.compiles"] == stats["compiles"]
        assert per_metric["executor.cache_hits"] == stats["cache_hits"]
        # the process-global aggregate covers this executor too
        global_counters = obs.telemetry_snapshot()["counters"]
        assert global_counters["executor.calls"] >= stats["calls"]

    def test_fused_collection_path(self):
        coll = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
                "confmat": MulticlassConfusionMatrix(num_classes=NUM_CLASSES, validate_args=False),
            }
        )
        for seed in range(2):
            coll.update(*_batch(seed=seed))
        # the first update resolves compute groups eagerly; the fused
        # executor engages from the second call on
        stats = coll.executor_status["stats"]
        per_coll = obs.telemetry_snapshot(coll)["counters"]
        assert stats["calls"] >= 1
        assert per_coll["executor.calls"] == stats["calls"]
        assert obs.telemetry_snapshot()["counters"]["executor.calls"] >= stats["calls"]
        assert any(n.startswith("tm_tpu.dispatch/MetricCollection") for n in _span_names())

    def test_deferred_path_emits_reduce_span(self):
        mesh = Mesh(np.array(jax.devices()[:NUM_DEVICES]), ("batch",))
        coll = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)}
        )
        coll.resolve_compute_groups(*_batch())
        step = make_deferred_collection_step(coll, mesh, axis_name="batch")
        logits, target = _batch()
        logits = jax.device_put(logits, NamedSharding(mesh, P("batch")))
        target = jax.device_put(target, NamedSharding(mesh, P("batch")))
        st = step.local_step(step.init_states(), logits, target)
        step.reduce(st)
        names = _span_names()
        assert any(n.startswith("tm_tpu.dispatch/") for n in names)
        assert obs.SPAN_REDUCE in names

    def test_background_compile_path(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "1")
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(tmp_path))
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.set_background_compile(True)
        m.update(*_batch())  # cold key: served eagerly, compile on the worker
        assert compile_cache.drain_worker(timeout=60.0)
        stats = m.executor_status["stats"]
        per_metric = obs.telemetry_snapshot(m)["counters"]
        assert per_metric["executor.eager_misses"] == stats["eager_misses"] >= 1
        assert per_metric["executor.background_compiles"] == stats["background_compiles"]
        if stats["background_compiles"]:
            assert any(
                e.name == obs.SPAN_COMPILE and (e.attrs or {}).get("background")
                for e in obs.peek_events()
            )

    def test_aggregate_releases_dropped_executors(self):
        before = obs.telemetry_snapshot()["counters"].get("executor.instances", 0)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        during = obs.telemetry_snapshot()["counters"]["executor.instances"]
        assert during >= before + 1
        del m
        import gc

        gc.collect()
        after = obs.telemetry_snapshot()["counters"].get("executor.instances", 0)
        assert after <= during - 1


# ---------------------------------------------------------------------------
# ring buffer semantics
# ---------------------------------------------------------------------------


class TestRingBuffer:
    def test_wrap_keeps_newest_events(self):
        obs.reset_ring(capacity=16)
        for i in range(50):
            obs.record_span(f"s{i}", i, i + 1)
        events = obs.drain_events()
        assert len(events) == 16
        assert [e.name for e in events] == [f"s{i}" for i in range(34, 50)]
        stats = obs.ring_stats()
        assert stats["recorded_total"] == 50 and stats["dropped_total"] == 34

    def test_drain_clears_and_preserves_order(self):
        with obs.span("a"):
            with obs.span("b"):
                pass
        events = obs.drain_events()
        assert [e.name for e in events] == ["b", "a"]  # ordered by span end
        assert obs.peek_events() == []

    def test_concurrent_recording_loses_nothing_under_capacity(self):
        obs.reset_ring(capacity=4096)

        def worker(k):
            for i in range(100):
                obs.record_span(f"t{k}", i, i + 1)

        threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(obs.drain_events()) == 800
        assert obs.ring_stats()["dropped_total"] == 0


# ---------------------------------------------------------------------------
# zero-cost-when-off
# ---------------------------------------------------------------------------


class TestTelemetryOff:
    def test_tracing_off_leaves_zero_events(self):
        obs.set_tracing(False)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        assert obs.peek_events() == []
        assert obs.ring_stats()["recorded_total"] == 0

    def test_telemetry_off_drops_counters_and_breadcrumbs(self):
        obs.set_telemetry(False)
        obs.counter_inc("x.y")
        obs.gauge_set("g", 1.0)
        obs.breadcrumb("k", {"a": 1})
        snap = obs.telemetry_snapshot()
        assert snap["telemetry_enabled"] is False
        assert "x.y" not in snap["counters"] and not snap["gauges"]
        assert obs.dump_diagnostics()["breadcrumbs"] == []

    def test_telemetry_off_disables_tracing_too(self):
        obs.set_telemetry(False)
        obs.set_tracing(True)  # must not engage under master-off
        assert not obs.tracing_enabled()
        with obs.span("x"):
            pass
        assert obs.peek_events() == []


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


class TestExporters:
    def test_chrome_trace_roundtrips_as_valid_json(self, tmp_path):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        for seed in range(3):
            m.update(*_batch(seed=seed))
        path = str(tmp_path / "trace.json")
        obs.write_chrome_trace(path)
        with open(path) as fh:
            doc = json.load(fh)
        events = doc["traceEvents"]
        assert events, "export produced no events"
        for ev in events:
            assert ev["ph"] == "X"
            assert isinstance(ev["ts"], (int, float)) and isinstance(ev["dur"], (int, float))
            assert ev["dur"] >= 0
            assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
            assert isinstance(ev["name"], str) and ev["name"]
        assert any(ev["name"].startswith("tm_tpu.dispatch/") for ev in events)

    def test_hundred_step_run_shows_all_seam_spans(self, tmp_path):
        """The acceptance walkthrough: a 100-step run's export carries
        dispatch/update/reduce/compile/checkpoint spans, loadable as a Chrome
        trace (Perfetto consumes exactly this schema)."""
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        for step_i in range(100):
            m.update(*_batch(seed=step_i % 7))
        m.compute()
        tm.save_state(m, str(tmp_path / "snap.ckpt"))
        # the deferred read point contributes the reduce span of a real run
        mesh = Mesh(np.array(jax.devices()[:NUM_DEVICES]), ("batch",))
        coll = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)}
        )
        coll.resolve_compute_groups(*_batch())
        step = make_deferred_collection_step(coll, mesh, axis_name="batch")
        logits, target = _batch()
        st = step.local_step(
            step.init_states(),
            jax.device_put(logits, NamedSharding(mesh, P("batch"))),
            jax.device_put(target, NamedSharding(mesh, P("batch"))),
        )
        step.reduce(st)
        doc = obs.chrome_trace(drain=True)
        names = {ev["name"] for ev in doc["traceEvents"]}
        for expected in (
            "tm_tpu.dispatch/MulticlassAccuracy",
            "tm_tpu.update/MulticlassAccuracy",
            "tm_tpu.compute/MulticlassAccuracy",
            obs.SPAN_REDUCE,
            obs.SPAN_COMPILE,
            "tm_tpu.checkpoint.save",
        ):
            assert expected in names, f"{expected} missing from trace ({sorted(names)[:20]})"
        # far more warm dispatches than compiles: the trace can attribute them
        dispatches = [e for e in doc["traceEvents"] if e["name"].startswith("tm_tpu.dispatch/")]
        compiles = [e for e in doc["traceEvents"] if e["name"] == obs.SPAN_COMPILE]
        assert len(dispatches) >= 100 and 1 <= len(compiles) < 10

    def test_prometheus_text_format(self):
        obs.counter_inc("checkpoint.saves", 2)
        obs.gauge_set("autosave.inflight", 1)
        text = obs.prometheus_text()
        assert "# TYPE tm_tpu_checkpoint_saves_total counter" in text
        assert "tm_tpu_checkpoint_saves_total 2" in text
        assert "# TYPE tm_tpu_autosave_inflight gauge" in text
        for line in text.strip().splitlines():
            assert line.startswith("#") or len(line.split(" ")) == 2

    def test_periodic_exporter_emits_records(self):
        seen = []
        exporter = obs.PeriodicExporter(interval_s=0.05, sink=seen.append).start()
        with obs.span("tick"):
            pass
        time.sleep(0.3)
        exporter.stop()
        assert exporter.stats["ticks"] >= 2 and exporter.stats["sink_errors"] == 0
        assert any("tick" in rec.get("spans_by_name", {}) for rec in seen)
        assert all("telemetry" in rec for rec in seen)

    def test_periodic_exporter_survives_sink_errors(self):
        def bad_sink(_rec):
            raise RuntimeError("scraper down")

        exporter = obs.PeriodicExporter(interval_s=0.05, sink=bad_sink).start()
        time.sleep(0.15)
        exporter.stop()
        assert exporter.stats["sink_errors"] >= 1
        assert exporter.stats["ticks"] >= 1  # the loop survived


# ---------------------------------------------------------------------------
# nesting under concurrency
# ---------------------------------------------------------------------------


def _assert_well_nested(events):
    """Per thread, any two spans must be disjoint or strictly nested —
    partial overlap would mean the tracer mis-timed an enter/exit."""
    by_tid = {}
    for e in events:
        by_tid.setdefault(e.tid, []).append(e)
    for tid, evs in by_tid.items():
        for i, a in enumerate(evs):
            for b in evs[i + 1 :]:
                lo, hi = max(a.t_start_ns, b.t_start_ns), min(a.t_end_ns, b.t_end_ns)
                if lo < hi:  # they overlap: must be containment
                    assert (
                        (a.t_start_ns <= b.t_start_ns and b.t_end_ns <= a.t_end_ns)
                        or (b.t_start_ns <= a.t_start_ns and a.t_end_ns <= b.t_end_ns)
                    ), f"partial overlap on tid {tid}: {a.name} vs {b.name}"


class TestNesting:
    def test_spans_nest_under_concurrent_bg_compile_and_autosave(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHMETRICS_TPU_COMPILE_AHEAD", "1")
        monkeypatch.setenv("TORCHMETRICS_TPU_CACHE_DIR", str(tmp_path / "cache"))
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.set_background_compile(True)
        saver = tm.Autosaver(m, str(tmp_path / "ckpt"), every_n_updates=2).attach()
        try:
            for step_i in range(8):
                # vary the batch size across bucket rungs: cold keys keep the
                # background worker compiling while autosaves fire
                n = 16 + 8 * (step_i % 3)
                m.update(*_batch(n=n, seed=step_i))
            saver.flush()
        finally:
            saver.detach()
        assert compile_cache.drain_worker(timeout=60.0)
        events = obs.drain_events()
        assert len({e.tid for e in events}) >= 2, "expected spans from worker threads too"
        _assert_well_nested(events)
        names = {e.name for e in events}
        assert any(n.startswith("tm_tpu.dispatch/") or n.startswith("tm_tpu.update/") for n in names)
        assert obs.SPAN_AUTOSAVE in names and "tm_tpu.checkpoint.save" in names

    def test_update_span_contains_dispatch_span(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        events = obs.drain_events()
        update = [e for e in events if e.name == "tm_tpu.update/MulticlassAccuracy"]
        dispatch = [e for e in events if e.name == "tm_tpu.dispatch/MulticlassAccuracy"]
        assert update and dispatch
        u, d = update[-1], dispatch[-1]
        assert u.t_start_ns <= d.t_start_ns and d.t_end_ns <= u.t_end_ns


# ---------------------------------------------------------------------------
# units, breadcrumbs, diagnostics, async observation
# ---------------------------------------------------------------------------


class TestUnitsAndDiagnostics:
    def test_compile_duration_standardized_on_us(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        stats = m.executor_status["stats"]
        assert stats["compile_us_total"] > 0
        # the one-release deprecated alias is gone (ISSUE 7 satellite)
        assert "compile_ms_total" not in stats
        # every duration-ish stats key carries the _us suffix
        for key in stats:
            assert not (key.endswith(("_ms", "_s", "_seconds")) or "_ms_" in key), (
                f"non-_us duration key {key!r}"
            )

    def test_executor_status_still_reports_last_reduce_us(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        assert "last_reduce_us" in m.executor_status

    def test_watchdog_stall_routes_breadcrumb(self):
        from torchmetrics_tpu.io.retry import stall_watchdog
        from torchmetrics_tpu.utils.exceptions import DispatchStallError

        with pytest.raises(DispatchStallError):
            with stall_watchdog(0.1, what="test hang", status=lambda: {"calls": 1}):
                time.sleep(2.0)
        crumbs = obs.dump_diagnostics()["breadcrumbs"]
        stalls = [c for c in crumbs if c["kind"] == "dispatch_stall"]
        assert stalls and stalls[-1]["data"]["what"] == "test hang"
        assert obs.telemetry_snapshot()["counters"]["watchdog.stalls"] >= 1

    def test_rollback_counts(self):
        from torchmetrics_tpu.testing import faults

        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        before = obs.telemetry_snapshot()["counters"].get("rollback.count", 0)
        with faults.raise_in_update(m):
            with pytest.raises(faults.FaultInjected):
                m.update(*_batch())
        assert obs.telemetry_snapshot()["counters"].get("rollback.count", 0) == before + 1

    def test_dump_diagnostics_shape(self):
        d = obs.dump_diagnostics()
        assert set(d) >= {"time_unix", "telemetry", "breadcrumbs", "env", "versions"}
        assert d["versions"]["jax"] == jax.__version__
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        per = obs.dump_diagnostics(m)
        assert per["telemetry"]["scope"] == "MulticlassAccuracy"

    def test_observe_ready_records_without_blocking(self):
        x = jnp.arange(1024.0)
        y = (x * 2).sum()  # async dispatch in flight
        out = obs.observe_ready("tm_tpu.device_ready", y, what="test")
        assert out is y  # the value passes straight through
        assert obs.flush_ready_observations(timeout=10.0)
        events = [e for e in obs.drain_events() if e.name == "tm_tpu.device_ready"]
        assert len(events) == 1 and events[0].attrs == {"what": "test"}

    def test_span_records_error_attr_on_exception(self):
        with pytest.raises(ValueError):
            with obs.span("failing"):
                raise ValueError("boom")
        events = obs.drain_events()
        assert events[-1].name == "failing" and events[-1].attrs["error"] == "ValueError"

    def test_counter_rejects_negative(self):
        with pytest.raises(ValueError):
            obs.counter_inc("x", -1)

    def test_checkpoint_counters_and_spans(self, tmp_path):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        m.update(*_batch())
        path = tm.save_state(m, str(tmp_path / "s.ckpt"))
        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)
        tm.restore_state(path, m2)
        counters = obs.telemetry_snapshot()["counters"]
        assert counters["checkpoint.saves"] >= 1 and counters["checkpoint.restores"] >= 1
        names = _span_names()
        assert "tm_tpu.checkpoint.save" in names and "tm_tpu.checkpoint.restore" in names
