"""Retrieval parameter-grid parity vs the reference oracle.

Depth complement for the retrieval domain: the reference enumerates
``empty_target_action x ignore_index x top_k`` per metric (reference
tests/unittests/retrieval/helpers.py:_default_metric_class_input_arguments and
the per-metric test modules); this sweeps the same axes through the modular
classes, which exercises the padded per-query grid
(functional/retrieval/_padded.py) against torch's per-query group loop.
"""
import sys

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # oracle parameter grids; run with --runslow

sys.path.insert(0, "/root/repo/tests")

from helpers.reference import load_reference_torchmetrics  # noqa: E402

load_reference_torchmetrics()

import torch  # noqa: E402

import torchmetrics_tpu.retrieval as ORM  # noqa: E402

N_Q, N_DOCS = 12, 96
rng = np.random.RandomState(77)
PREDS = rng.rand(N_DOCS).astype(np.float32)
TARGET = rng.randint(0, 2, N_DOCS)
INDEXES = np.sort(rng.randint(0, N_Q, N_DOCS))
# make two query groups all-negative so empty_target_action branches differ
for q in (2, 7):
    TARGET[INDEXES == q] = 0

CLASSES = [
    ("RetrievalMAP", {}),
    ("RetrievalMRR", {}),
    ("RetrievalPrecision", {"top_k": 4}),
    ("RetrievalRecall", {"top_k": 4}),
    ("RetrievalHitRate", {"top_k": 4}),
    ("RetrievalFallOut", {"top_k": 4}),
    ("RetrievalNormalizedDCG", {"top_k": 4}),
    ("RetrievalRPrecision", {}),
    ("RetrievalAUROC", {}),
]


def _run_pair(cls_name, kwargs):
    import torchmetrics.retrieval as RRM

    ours = getattr(ORM, cls_name)(**kwargs)
    theirs = getattr(RRM, cls_name)(**kwargs)
    ours.update(jnp.asarray(PREDS), jnp.asarray(TARGET), indexes=jnp.asarray(INDEXES))
    theirs.update(
        torch.from_numpy(PREDS), torch.from_numpy(TARGET), indexes=torch.from_numpy(INDEXES)
    )
    return np.asarray(ours.compute(), dtype=np.float64), theirs.compute().numpy().astype(np.float64)


@pytest.mark.parametrize("cls_name,extra", CLASSES)
@pytest.mark.parametrize("empty_target_action", ["skip", "neg", "pos"])
def test_empty_target_action_grid(cls_name, extra, empty_target_action):
    # NB for RetrievalFallOut "empty" means all-POSITIVE queries; the axis
    # still applies verbatim, the reference just triggers it on that condition
    kwargs = {"empty_target_action": empty_target_action, **extra}
    a, b = _run_pair(cls_name, kwargs)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} {kwargs}")


@pytest.mark.parametrize("cls_name,extra", CLASSES)
def test_ignore_index_grid(cls_name, extra):
    target = TARGET.copy()
    target[rng.rand(N_DOCS) < 0.1] = -1
    import torchmetrics.retrieval as RRM

    kwargs = {"ignore_index": -1, "empty_target_action": "skip", **extra}
    ours = getattr(ORM, cls_name)(**kwargs)
    theirs = getattr(RRM, cls_name)(**kwargs)
    ours.update(jnp.asarray(PREDS), jnp.asarray(target), indexes=jnp.asarray(INDEXES))
    theirs.update(
        torch.from_numpy(PREDS), torch.from_numpy(target), indexes=torch.from_numpy(INDEXES)
    )
    np.testing.assert_allclose(
        np.asarray(ours.compute(), dtype=np.float64),
        theirs.compute().numpy().astype(np.float64),
        atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} ignore_index",
    )


@pytest.mark.parametrize("cls_name", ["RetrievalPrecision", "RetrievalRecall", "RetrievalNormalizedDCG"])
@pytest.mark.parametrize("top_k", [1, 2, 8, None])
def test_top_k_grid(cls_name, top_k):
    kwargs = {} if top_k is None else {"top_k": top_k}
    kwargs["empty_target_action"] = "neg"
    a, b = _run_pair(cls_name, kwargs)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} top_k={top_k}")
