"""Retrieval parameter-grid parity vs the reference oracle.

Depth complement for the retrieval domain: the reference enumerates
``empty_target_action x ignore_index x top_k`` per metric (reference
tests/unittests/retrieval/helpers.py:_default_metric_class_input_arguments and
the per-metric test modules); this sweeps the same axes through the modular
classes, which exercises the padded per-query grid
(functional/retrieval/_padded.py) against torch's per-query group loop.
"""
import sys

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # oracle parameter grids; run with --runslow

sys.path.insert(0, "/root/repo/tests")

from helpers.reference import load_reference_torchmetrics  # noqa: E402

load_reference_torchmetrics()

import torch  # noqa: E402

import torchmetrics_tpu.retrieval as ORM  # noqa: E402

N_Q, N_DOCS = 12, 96
rng = np.random.RandomState(77)
PREDS = rng.rand(N_DOCS).astype(np.float32)
TARGET = rng.randint(0, 2, N_DOCS)
INDEXES = np.sort(rng.randint(0, N_Q, N_DOCS))
# make two query groups all-negative so empty_target_action branches differ
for q in (2, 7):
    TARGET[INDEXES == q] = 0

CLASSES = [
    ("RetrievalMAP", {}),
    ("RetrievalMRR", {}),
    ("RetrievalPrecision", {"top_k": 4}),
    ("RetrievalRecall", {"top_k": 4}),
    ("RetrievalHitRate", {"top_k": 4}),
    ("RetrievalFallOut", {"top_k": 4}),
    ("RetrievalNormalizedDCG", {"top_k": 4}),
    ("RetrievalRPrecision", {}),
    ("RetrievalAUROC", {}),
]


def _run_pair(cls_name, kwargs, target=None):
    import torchmetrics.retrieval as RRM

    target = TARGET if target is None else target
    ours = getattr(ORM, cls_name)(**kwargs)
    theirs = getattr(RRM, cls_name)(**kwargs)
    ours.update(jnp.asarray(PREDS), jnp.asarray(target), indexes=jnp.asarray(INDEXES))
    theirs.update(
        torch.from_numpy(PREDS), torch.from_numpy(target), indexes=torch.from_numpy(INDEXES)
    )
    return np.asarray(ours.compute(), dtype=np.float64), theirs.compute().numpy().astype(np.float64)


@pytest.mark.parametrize("cls_name,extra", CLASSES)
@pytest.mark.parametrize("empty_target_action", ["skip", "neg", "pos"])
def test_empty_target_action_grid(cls_name, extra, empty_target_action):
    # NB for RetrievalFallOut "empty" means all-POSITIVE queries; the axis
    # still applies verbatim, the reference just triggers it on that condition
    kwargs = {"empty_target_action": empty_target_action, **extra}
    a, b = _run_pair(cls_name, kwargs)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} {kwargs}")


@pytest.mark.parametrize("cls_name,extra", CLASSES)
def test_ignore_index_grid(cls_name, extra):
    target = TARGET.copy()
    target[rng.rand(N_DOCS) < 0.1] = -1

    kwargs = {"ignore_index": -1, "empty_target_action": "skip", **extra}
    a, b = _run_pair(cls_name, kwargs, target=target)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} ignore_index")


@pytest.mark.parametrize("cls_name", ["RetrievalPrecision", "RetrievalRecall", "RetrievalNormalizedDCG"])
@pytest.mark.parametrize("top_k", [1, 2, 8, None])
def test_top_k_grid(cls_name, top_k):
    kwargs = {} if top_k is None else {"top_k": top_k}
    kwargs["empty_target_action"] = "neg"
    a, b = _run_pair(cls_name, kwargs)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} top_k={top_k}")


def _np_custom_aggregate(values, dim=None):
    """A deliberately asymmetric custom aggregation (q75), exercised on both
    sides — mirrors the reference's _custom_aggregate_fn axis
    (reference tests/unittests/retrieval/test_map.py:57)."""
    import torch as _t

    if isinstance(values, _t.Tensor):
        return _t.quantile(values, 0.75)
    return jnp.quantile(values, 0.75)


@pytest.mark.parametrize("cls_name,extra", [("RetrievalMAP", {}), ("RetrievalPrecision", {"top_k": 3})])
@pytest.mark.parametrize("aggregation", ["mean", "median", "max", "min", _np_custom_aggregate])
def test_aggregation_grid(cls_name, extra, aggregation):
    """Reference axis: per-query values fold with mean/median/max/min or a
    user callable (reference retrieval/base.py:28-44)."""
    kwargs = {"aggregation": aggregation, "empty_target_action": "neg", **extra}
    a, b = _run_pair(cls_name, kwargs)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"{cls_name} agg={aggregation}")


@pytest.mark.parametrize("empty_target_action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("top_k", [None, 1, 4, 10])
def test_joint_axes_grid(empty_target_action, ignore_index, top_k):
    """The reference's full class-test cross product (test_map.py:53-58) on
    one representative metric: every axis combination, not just marginals."""

    target = TARGET.copy()
    if ignore_index is not None:
        target[np.random.RandomState(3).rand(N_DOCS) < 0.1] = ignore_index
    kwargs = {"empty_target_action": empty_target_action, "ignore_index": ignore_index}
    if top_k is not None:
        kwargs["top_k"] = top_k
    a, b = _run_pair("RetrievalPrecision", kwargs, target=target)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4, err_msg=f"joint {kwargs}")
