"""Retrieval metric parity tests vs the PyTorch reference implementation."""
import sys

import numpy as np
import pytest
import torch

sys.path.insert(0, "/root/repo/tests")
from helpers.reference import load_reference_torchmetrics  # noqa: E402

ref_tm = load_reference_torchmetrics()
from torchmetrics.functional.retrieval import (  # noqa: E402
    retrieval_auroc as ref_auroc,
    retrieval_average_precision as ref_ap,
    retrieval_fall_out as ref_fo,
    retrieval_hit_rate as ref_hr,
    retrieval_normalized_dcg as ref_ndcg,
    retrieval_precision as ref_prec,
    retrieval_precision_recall_curve as ref_prc,
    retrieval_r_precision as ref_rprec,
    retrieval_recall as ref_rec,
    retrieval_reciprocal_rank as ref_rr,
)
from torchmetrics import retrieval as ref_retrieval_mod  # noqa: E402

import torchmetrics_tpu.functional as F  # noqa: E402
import torchmetrics_tpu as tm  # noqa: E402

rng = np.random.RandomState(13)
N = 200
INDEXES = rng.randint(0, 12, size=N).astype(np.int64)
PREDS = rng.rand(N).astype(np.float32)
TARGET = (rng.rand(N) > 0.7).astype(np.int64)
# one query guaranteed positive-free and one guaranteed with positives
TARGET[INDEXES == 3] = 0
TARGET[np.where(INDEXES == 5)[0][0]] = 1

QUERY_P = rng.rand(20).astype(np.float32)
QUERY_T = (rng.rand(20) > 0.6).astype(np.int64)

FUNCTIONAL_CASES = [
    (F.retrieval_average_precision, ref_ap, {}),
    (F.retrieval_average_precision, ref_ap, {"top_k": 5}),
    (F.retrieval_reciprocal_rank, ref_rr, {}),
    (F.retrieval_reciprocal_rank, ref_rr, {"top_k": 3}),
    (F.retrieval_precision, ref_prec, {}),
    (F.retrieval_precision, ref_prec, {"top_k": 4}),
    (F.retrieval_precision, ref_prec, {"top_k": 40, "adaptive_k": True}),
    (F.retrieval_recall, ref_rec, {}),
    (F.retrieval_recall, ref_rec, {"top_k": 4}),
    (F.retrieval_fall_out, ref_fo, {"top_k": 6}),
    (F.retrieval_hit_rate, ref_hr, {"top_k": 3}),
    (F.retrieval_r_precision, ref_rprec, {}),
    (F.retrieval_normalized_dcg, ref_ndcg, {}),
    (F.retrieval_normalized_dcg, ref_ndcg, {"top_k": 7}),
    (F.retrieval_auroc, ref_auroc, {}),
    (F.retrieval_auroc, ref_auroc, {"top_k": 10}),
    (F.retrieval_auroc, ref_auroc, {"max_fpr": 0.5}),
]


@pytest.mark.parametrize("ours,ref,kw", FUNCTIONAL_CASES, ids=[f"{r.__name__}-{k}" for _, r, k in FUNCTIONAL_CASES])
def test_functional_parity(ours, ref, kw):
    got = np.asarray(ours(QUERY_P, QUERY_T, **kw))
    want = ref(torch.from_numpy(QUERY_P), torch.from_numpy(QUERY_T), **kw).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)


def test_functional_ndcg_nonbinary():
    t = rng.randint(0, 4, size=20).astype(np.int64)
    got = np.asarray(F.retrieval_normalized_dcg(QUERY_P, t))
    want = ref_ndcg(torch.from_numpy(QUERY_P), torch.from_numpy(t)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)


def test_functional_ndcg_with_ties():
    p = np.round(QUERY_P * 4) / 4  # heavy ties
    got = np.asarray(F.retrieval_normalized_dcg(p.astype(np.float32), QUERY_T))
    want = ref_ndcg(torch.from_numpy(p.astype(np.float32)), torch.from_numpy(QUERY_T)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)


def test_functional_prc():
    for kw in [{}, {"max_k": 5}, {"max_k": 30, "adaptive_k": True}]:
        gp, gr, gk = F.retrieval_precision_recall_curve(QUERY_P, QUERY_T, **kw)
        wp, wr, wk = ref_prc(torch.from_numpy(QUERY_P), torch.from_numpy(QUERY_T), **kw)
        np.testing.assert_allclose(np.asarray(gp), wp.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gr), wr.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gk), wk.numpy())


MODULAR_CASES = [
    (tm.RetrievalMAP, "RetrievalMAP", {}),
    (tm.RetrievalMRR, "RetrievalMRR", {}),
    (tm.RetrievalPrecision, "RetrievalPrecision", {"top_k": 3}),
    (tm.RetrievalRecall, "RetrievalRecall", {"top_k": 3}),
    (tm.RetrievalFallOut, "RetrievalFallOut", {"top_k": 3}),
    (tm.RetrievalHitRate, "RetrievalHitRate", {"top_k": 3}),
    (tm.RetrievalRPrecision, "RetrievalRPrecision", {}),
    (tm.RetrievalNormalizedDCG, "RetrievalNormalizedDCG", {}),
    (tm.RetrievalAUROC, "RetrievalAUROC", {}),
]


@pytest.mark.parametrize("cls,ref_name,kw", MODULAR_CASES, ids=[c[1] for c in MODULAR_CASES])
@pytest.mark.parametrize("empty_target_action", ["neg", "pos", "skip"])
def test_modular_parity(cls, ref_name, kw, empty_target_action):
    ours = cls(empty_target_action=empty_target_action, **kw)
    ref = getattr(ref_retrieval_mod, ref_name)(empty_target_action=empty_target_action, **kw)
    # two-batch update
    half = N // 2
    for sl in (slice(0, half), slice(half, N)):
        ours.update(PREDS[sl], TARGET[sl], INDEXES[sl])
        ref.update(torch.from_numpy(PREDS[sl]), torch.from_numpy(TARGET[sl]), indexes=torch.from_numpy(INDEXES[sl]))
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("aggregation", ["median", "min", "max"])
def test_aggregation_modes(aggregation):
    ours = tm.RetrievalMAP(aggregation=aggregation)
    ref = ref_retrieval_mod.RetrievalMAP(aggregation=aggregation)
    ours.update(PREDS, TARGET, INDEXES)
    ref.update(torch.from_numpy(PREDS), torch.from_numpy(TARGET), indexes=torch.from_numpy(INDEXES))
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5)


def test_empty_target_error():
    m = tm.RetrievalMAP(empty_target_action="error")
    m.update(PREDS, TARGET, INDEXES)
    with pytest.raises(ValueError, match="no positive target"):
        m.compute()


def test_ignore_index():
    t = TARGET.copy()
    t[::7] = -1
    ours = tm.RetrievalMAP(ignore_index=-1)
    ref = ref_retrieval_mod.RetrievalMAP(ignore_index=-1)
    ours.update(PREDS, t, INDEXES)
    ref.update(torch.from_numpy(PREDS), torch.from_numpy(t), indexes=torch.from_numpy(INDEXES))
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5)


def test_prc_modular():
    for kw in [{"max_k": 4}, {}]:
        ours = tm.RetrievalPrecisionRecallCurve(**kw)
        ref = ref_retrieval_mod.RetrievalPrecisionRecallCurve(**kw)
        ours.update(PREDS, TARGET, INDEXES)
        ref.update(torch.from_numpy(PREDS), torch.from_numpy(TARGET), indexes=torch.from_numpy(INDEXES))
        gp, gr, gk = ours.compute()
        wp, wr, wk = ref.compute()
        np.testing.assert_allclose(np.asarray(gp), wp.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gr), wr.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gk), wk.numpy())


def test_recall_at_fixed_precision():
    ours = tm.RetrievalRecallAtFixedPrecision(min_precision=0.3, max_k=6)
    ref = ref_retrieval_mod.RetrievalRecallAtFixedPrecision(min_precision=0.3, max_k=6)
    ours.update(PREDS, TARGET, INDEXES)
    ref.update(torch.from_numpy(PREDS), torch.from_numpy(TARGET), indexes=torch.from_numpy(INDEXES))
    g_recall, g_k = ours.compute()
    w_recall, w_k = ref.compute()
    np.testing.assert_allclose(np.asarray(g_recall), w_recall.numpy(), atol=1e-5)
    assert int(g_k) == int(w_k)


def test_auroc_max_fpr_single_class():
    # all-positive / all-negative queries must skip the McClish correction
    p = np.asarray([0.3, 0.2, 0.1], dtype=np.float32)
    for t in (np.asarray([1, 1, 1]), np.asarray([0, 0, 0])):
        got = np.asarray(F.retrieval_auroc(p, t, top_k=2, max_fpr=0.5))
        want = ref_auroc(torch.from_numpy(p), torch.from_numpy(t), top_k=2, max_fpr=0.5).numpy()
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_ndcg_nonbinary_negative_ragged():
    # query shorter than max_docs with negative relevance: padding must not
    # outrank negative values in the ideal ordering
    idx = np.asarray([0, 0, 0, 0, 0, 0, 1, 1, 1], dtype=np.int64)
    p = rng.rand(9).astype(np.float32)
    t = np.asarray([1, 0, 2, 0, 1, 0, 2, -1, 1], dtype=np.int64)
    ours = tm.RetrievalNormalizedDCG()
    ref = ref_retrieval_mod.RetrievalNormalizedDCG()
    ours.update(p, t, idx)
    ref.update(torch.from_numpy(p), torch.from_numpy(t), indexes=torch.from_numpy(idx))
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5)


def test_update_validation():
    m = tm.RetrievalMAP()
    with pytest.raises(ValueError, match="cannot be None"):
        m.update(PREDS, TARGET, None)
    with pytest.raises(ValueError, match="same shape"):
        m.update(PREDS[:5], TARGET[:6], INDEXES[:6])
    with pytest.raises(ValueError, match="long integers"):
        m.update(PREDS, TARGET, INDEXES.astype(np.float32))
    with pytest.raises(ValueError, match="binary"):
        m.update(PREDS, TARGET * 5, INDEXES)
