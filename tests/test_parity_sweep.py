"""Reference-oracle parity swept across every buildable metric class.

Complements the targeted per-domain parity tests with a breadth sweep: for each
metric class the doctest-generator registry can build, instantiate the
SAME-NAMED reference class with the SAME constructor kwargs (constructor-
signature parity is itself part of the claim), feed both the same inputs, and
assert the computed values agree. Classes whose reference needs an external
wheel (pesq/pystoi/gammatone/torch-fidelity/pycocotools) or a model hook are
excluded.
"""
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # reference-oracle sweep over ~175 classes; run with --runslow

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
import gen_doctests as reg  # noqa: E402

from helpers.reference import load_reference_torchmetrics  # noqa: E402
from test_lifecycle_sweep import CASES, _build  # noqa: E402

import torch  # noqa: E402

# reference classes that cannot run in this environment or take different
# arguments by design (TPU-extension kwargs, hook-based models, external wheels)
PARITY_SKIP = {
    # external wheels the reference imports lazily
    "PerceptualEvaluationSpeechQuality", "ShortTimeObjectiveIntelligibility",
    "SpeechReverberationModulationEnergyRatio",
    # registry ctor uses our TPU-specific argument spelling (PIT's batched
    # metric_func; CLIP's embedding_fn hook replacing the HF-download path)
    "PermutationInvariantTraining", "CLIPScore", "CLIPImageQualityAssessment",
    # the reference's exact-mode curve classes return ragged lists; covered by
    # dedicated tests in tests/classification/test_curves.py
    "RetrievalPrecisionRecallCurve", "RetrievalRecallAtFixedPrecision",
    # reference's default rouge_keys include rougeLsum -> needs the nltk punkt
    # asset (zero-egress env); value parity covered by tests/text/test_text.py
    # and the real-fixture goldens (tests/test_real_fixtures.py)
    "ROUGEScore",
    # reference derives pan_lr via torchvision (not installed) when the update
    # omits it; value parity with explicit pan_lr covered in
    # tests/image/test_image_functional.py::TestPansharpening
    "SpatialDistortionIndex", "QualityWithNoReference",
}
# classes where float32-vs-float64 accumulation differences need a looser bound
LOOSE = {
    "KendallRankCorrCoef": 1e-3,
    "FleissKappa": 1e-3,
    # registry case has preds~=target: acos(dot~=1) sits at float32's noise
    # floor (~1e-4 rad), so both implementations return O(1e-4) with O(1e-5)
    # rounding scatter; dedicated tests cover the regime away from the floor
    "SpectralAngleMapper": 1e-4,
}


def _to_torch(v):
    if isinstance(v, jax.Array):
        t = torch.from_numpy(np.asarray(v).copy())
        # jax defaults to int32; the reference validates for torch's int64
        return t.long() if t.dtype in (torch.int32, torch.int16, torch.uint8) else t
    if isinstance(v, dict):
        return {k: _to_torch(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_to_torch(x) for x in v)
    return v


def _compare(ours, theirs, atol):
    if isinstance(ours, dict):
        assert isinstance(theirs, dict) and set(ours) == set(theirs), (sorted(ours), sorted(theirs))
        for k in ours:
            _compare(ours[k], theirs[k], atol)
    elif isinstance(ours, (list, tuple)):
        assert len(ours) == len(theirs)
        for a, b in zip(ours, theirs):
            _compare(a, b, atol)
    else:
        np.testing.assert_allclose(
            np.asarray(ours, dtype=np.float64),
            np.asarray(theirs.detach() if hasattr(theirs, "detach") else theirs, dtype=np.float64),
            rtol=1e-4, atol=atol,
        )


# wrapper ctor strings instantiate nested metrics, which would hand OUR classes
# to the reference wrapper; wrappers have dedicated parity tests elsewhere
PARITY_CASES = [
    c for c in CASES
    if c.id not in PARITY_SKIP and isinstance(c.values[4], str)
    and not c.values[0].startswith("torchmetrics_tpu.wrappers")
]


def _construct_reference(module_name, cls_name, ctor, ns):
    """Resolve the same-named reference class and construct it with OUR ctor
    kwargs (the constructor-signature half of the parity claim). Returns the
    torch-converted namespace with ``ref_m`` bound, or skips.

    NB the ctor expression must be exec'd with ``cls_name`` bound to the
    REFERENCE class in the one namespace used for name resolution: the build
    namespace also holds OUR class under the same name, and an earlier version
    that passed it as exec locals shadowed the reference — silently turning
    the whole sweep into ours-vs-ours.
    """
    import importlib

    load_reference_torchmetrics()
    domain = module_name.split(".")[1]
    ref_cls = None
    try:
        ref_cls = getattr(importlib.import_module(f"torchmetrics.{domain}"), cls_name, None)
    except ImportError:
        pass
    if ref_cls is None:
        ref_cls = getattr(importlib.import_module("torchmetrics"), cls_name, None)
    if ref_cls is None:
        pytest.skip(f"{cls_name} not exported by the reference")
    ref_ns = {k: _to_torch(v) for k, v in ns.items() if not k.startswith("__")}
    ref_ns[cls_name] = ref_cls
    try:
        exec(f"ref_m = {cls_name}(" + ctor + ")", ref_ns)
    except ModuleNotFoundError as e:
        pytest.skip(f"reference needs external wheel: {e}")
    assert type(ref_ns["ref_m"]).__module__.startswith("torchmetrics."), "must construct the reference class"
    return ref_ns


# Value parity for PARITY_SKIP classes lives in dedicated tests, but the
# constructor-signature half of the parity claim still applies to them —
# except where the TPU argument spelling differs by design.
_CTOR_DIFFERENT = {"PermutationInvariantTraining", "CLIPScore", "CLIPImageQualityAssessment"}
CTOR_ONLY_CASES = [
    c for c in CASES
    if c.id in (PARITY_SKIP - _CTOR_DIFFERENT) and isinstance(c.values[4], str)
]


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", CTOR_ONLY_CASES)
def test_ctor_signature_parity_excluded(module_name, cls_name, ctor, setup, upd):
    """The reference class must accept the same constructor kwargs, even where
    value parity is delegated to dedicated tests (external wheels, ragged
    exact-mode outputs)."""
    ns, _ = _build(module_name, cls_name, ctor, setup, upd)
    _construct_reference(module_name, cls_name, ctor, ns)


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", PARITY_CASES)
def test_reference_parity(module_name, cls_name, ctor, setup, upd):
    ns, upd = _build(module_name, cls_name, ctor, setup, upd)
    m = ns["m"]

    ref_ns = _construct_reference(module_name, cls_name, ctor, ns)
    ref_m = ref_ns["ref_m"]

    exec(f"m.update({upd})", ns)
    exec(f"m.update({upd})", ns)
    exec(f"ref_m.update({upd})", ref_ns)
    exec(f"ref_m.update({upd})", ref_ns)

    _compare(m.compute(), ref_m.compute(), LOOSE.get(cls_name, 1e-5))
