"""Reference-oracle parity swept across every buildable metric class.

Complements the targeted per-domain parity tests with a breadth sweep: for each
metric class the doctest-generator registry can build, instantiate the
SAME-NAMED reference class with the SAME constructor kwargs (constructor-
signature parity is itself part of the claim), feed both the same inputs, and
assert the computed values agree. Classes whose reference needs an external
wheel (pesq/pystoi/gammatone/torch-fidelity/pycocotools) or a model hook are
excluded.
"""
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
import gen_doctests as reg  # noqa: E402

from helpers.reference import load_reference_torchmetrics  # noqa: E402
from test_lifecycle_sweep import CASES, _build  # noqa: E402

import torch  # noqa: E402

# reference classes that cannot run in this environment or take different
# arguments by design (TPU-extension kwargs, hook-based models, external wheels)
PARITY_SKIP = {
    # external wheels the reference imports lazily
    "PerceptualEvaluationSpeechQuality", "ShortTimeObjectiveIntelligibility",
    "SpeechReverberationModulationEnergyRatio",
    # registry ctor uses our TPU-specific argument spelling
    "PermutationInvariantTraining",
    # the reference's exact-mode curve classes return ragged lists; covered by
    # dedicated tests in tests/classification/test_curves.py
    "RetrievalPrecisionRecallCurve", "RetrievalRecallAtFixedPrecision",
}
# classes where float32-vs-float64 accumulation differences need a looser bound
LOOSE = {"KendallRankCorrCoef": 1e-3, "FleissKappa": 1e-3}


def _to_torch(v):
    if isinstance(v, jax.Array):
        return torch.from_numpy(np.asarray(v).copy())
    if isinstance(v, dict):
        return {k: _to_torch(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_to_torch(x) for x in v)
    return v


def _compare(ours, theirs, atol):
    if isinstance(ours, dict):
        assert isinstance(theirs, dict) and set(ours) == set(theirs), (sorted(ours), sorted(theirs))
        for k in ours:
            _compare(ours[k], theirs[k], atol)
    elif isinstance(ours, (list, tuple)):
        assert len(ours) == len(theirs)
        for a, b in zip(ours, theirs):
            _compare(a, b, atol)
    else:
        np.testing.assert_allclose(
            np.asarray(ours, dtype=np.float64),
            np.asarray(theirs.detach() if hasattr(theirs, "detach") else theirs, dtype=np.float64),
            rtol=1e-4, atol=atol,
        )


# wrapper ctor strings instantiate nested metrics, which would hand OUR classes
# to the reference wrapper; wrappers have dedicated parity tests elsewhere
PARITY_CASES = [
    c for c in CASES
    if c.id not in PARITY_SKIP and isinstance(c.values[4], str)
    and not c.values[0].startswith("torchmetrics_tpu.wrappers")
]


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", PARITY_CASES)
def test_reference_parity(module_name, cls_name, ctor, setup, upd):
    import importlib

    load_reference_torchmetrics()
    domain = module_name.split(".")[1]
    ref_cls = None
    try:
        ref_cls = getattr(importlib.import_module(f"torchmetrics.{domain}"), cls_name, None)
    except ImportError:
        pass
    if ref_cls is None:
        ref_cls = getattr(importlib.import_module("torchmetrics"), cls_name, None)
    if ref_cls is None:
        pytest.skip(f"{cls_name} not exported by the reference")
    ns, upd = _build(module_name, cls_name, ctor, setup, upd)
    m = ns["m"]

    # same ctor kwargs must be accepted by the reference class (API parity)
    ref_ns = {k: _to_torch(v) for k, v in ns.items() if not k.startswith("__")}
    try:
        exec(f"ref_m = {cls_name}(" + ctor + ")", {**ref_ns, cls_name: ref_cls}, ref_ns)
    except ModuleNotFoundError as e:
        pytest.skip(f"reference needs external wheel: {e}")
    ref_m = ref_ns["ref_m"]

    exec(f"m.update({upd})", ns)
    exec(f"m.update({upd})", ns)
    exec(f"ref_m.update({upd})", ref_ns)
    exec(f"ref_m.update({upd})", ref_ns)

    _compare(m.compute(), ref_m.compute(), LOOSE.get(cls_name, 1e-5))
