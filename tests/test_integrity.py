"""Runtime state-integrity chaos suite (ISSUE 19).

Silent data corruption — a flipped bit from a mercurial core, a replica that
drifts after a reduce, an install-path H2D fault, a delta corrupted in
flight — must be *detected* by the fingerprint layer
(torchmetrics_tpu/integrity.py) and resolved per the ``on_divergence``
policy triple, never served/snapshotted/shipped as truth. The acceptance
properties exercised here:

- host (numpy) and device (jitted XLA) fingerprints agree bit-for-bit
  across every state dtype, and ANY single flipped bit changes them;
- a 1-bit flip injected between updates is caught within one audit interval
  in step mode (read-point verify) AND deferred mode (per-shard audit),
  with shard attribution for replica skew;
- ``"restore"`` converges bit-exact with the fault-free run; ``"degraded"``
  serves the last-good value with staleness attribution;
- recovery mirrors that diverge from the state they claim to equal rebuild
  instead of serving corrupt rollback rows;
- checkpoint restore re-fingerprints the INSTALLED state against the
  manifest and falls back through the rotation like a torn file;
- a fleet delta corrupted in flight hash-mismatches at the ledger, drops
  without merging, quarantines, and heals through the full resync —
  converging bit-exact.

Runs on the 8-fake-device CPU mesh from conftest.py. Exact float claims use
multiples of 1/8 so fp32 sums carry no rounding to hide behind.
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, "/root/repo/tests")

import torchmetrics_tpu as tm  # noqa: E402
from torchmetrics_tpu import Metric, MetricCollection, obs  # noqa: E402
from torchmetrics_tpu.fleet import (  # noqa: E402
    FleetTopology,
    LeafExporter,
    LeafLedger,
    Uplink,
    build_fleet,
    payload_checksum,
)
from torchmetrics_tpu.integrity import (  # noqa: E402
    DeferredIntegrity,
    IntegrityAuditor,
    device_fingerprints,
    device_shard_fingerprints,
    expanded_divergences,
    fingerprint_digest,
    host_fingerprints,
    host_leaf_fingerprint,
    replica_divergences,
)
from torchmetrics_tpu.io import restore_state, save_state  # noqa: E402
from torchmetrics_tpu.io.checkpoint import load_manifest  # noqa: E402
from torchmetrics_tpu.ops.async_read import drain_pipeline  # noqa: E402
from torchmetrics_tpu.ops.executor import make_deferred_collection_step  # noqa: E402
from torchmetrics_tpu.parallel.class_shard import ClassShardMirror  # noqa: E402
from torchmetrics_tpu.quarantine import DegradedValue, LaneStateMirror  # noqa: E402
from torchmetrics_tpu.testing import faults  # noqa: E402
from torchmetrics_tpu.utils.exceptions import (  # noqa: E402
    CheckpointCorruptionError,
    StateCorruptionError,
    StateDivergenceError,
)

NO_SLEEP = lambda s: None  # noqa: E731 — injected backoff clock


def _counter(name):
    return obs.telemetry_snapshot()["counters"].get(name, 0)


def _mesh(d=8):
    return Mesh(np.array(jax.devices()[:d]), ("batch",))


def _put(mesh, arr, spec=P("batch")):
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))


def _batches(n, seed=0, width=8):
    rng = np.random.RandomState(seed)
    return [(rng.randint(-40, 40, width) / 8.0).astype(np.float32) for _ in range(n)]


class _SumLike(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + x.sum()

    def compute(self):
        return self.total


# ---------------------------------------------------------------------------
# fingerprint primitives: host/device agreement, sensitivity, shard folds
# ---------------------------------------------------------------------------


class TestFingerprints:
    DTYPES = [
        np.float32,
        np.float64,
        np.int32,
        np.int64,
        np.uint8,
        np.int16,
        np.bool_,
    ]

    @pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: np.dtype(d).name)
    def test_host_device_agree(self, dtype):
        rng = np.random.RandomState(11)
        if dtype == np.bool_:
            arr = rng.rand(3, 5) > 0.5
        else:
            arr = (rng.randint(-100, 100, (3, 5))).astype(dtype)
        dev = np.asarray(list(device_fingerprints({"x": jnp.asarray(arr)}).values())[0])
        # fingerprint the DEVICE array's bits: jax may truncate 64-bit input
        host = host_leaf_fingerprint(np.asarray(jnp.asarray(arr)))
        np.testing.assert_array_equal(dev, host)
        assert dev.dtype == np.uint32 and dev.shape == (2,)

    def test_bfloat16_agrees(self):
        arr = jnp.arange(24, dtype=jnp.bfloat16).reshape(4, 6) / 8
        dev = np.asarray(list(device_fingerprints({"x": arr}).values())[0])
        host = host_leaf_fingerprint(np.asarray(arr))
        np.testing.assert_array_equal(dev, host)

    def test_single_bit_flip_changes_fingerprint(self):
        arr = (np.arange(32, dtype=np.float32) / 8.0).reshape(4, 8)
        clean = host_leaf_fingerprint(arr)
        for seed in range(8):
            bad, bits = faults._flip_bits_host(arr, 1, seed)
            assert len(bits) == 1
            assert not np.array_equal(host_leaf_fingerprint(bad), clean)

    def test_order_insensitive_and_empty(self):
        rng = np.random.RandomState(3)
        arr = rng.rand(64).astype(np.float32)
        shuffled = arr[rng.permutation(64)]
        np.testing.assert_array_equal(
            host_leaf_fingerprint(arr), host_leaf_fingerprint(shuffled)
        )
        np.testing.assert_array_equal(
            host_leaf_fingerprint(np.zeros((0,), np.float32)), np.zeros(2, np.uint32)
        )

    def test_shard_fps_match_per_row_host(self):
        stacked = jnp.asarray(
            (np.random.RandomState(5).randint(-100, 100, (8, 4)) / 8.0).astype(np.float32)
        )
        per_shard = np.asarray(list(device_shard_fingerprints({"s": stacked}).values())[0])
        assert per_shard.shape == (8, 2)
        host = np.asarray(stacked)
        for i in range(8):
            np.testing.assert_array_equal(per_shard[i], host_leaf_fingerprint(host[i]))

    def test_digest_deterministic_and_sensitive(self):
        state = {"a": np.arange(4, dtype=np.float32), "b": np.asarray(7, np.int64)}
        d1 = fingerprint_digest(host_fingerprints(state))
        d2 = fingerprint_digest(host_fingerprints({k: np.array(v) for k, v in state.items()}))
        assert d1 == d2 and len(d1) == 64
        bad, _ = faults._flip_bits_host(state["a"], 1, 0)
        assert fingerprint_digest(host_fingerprints({**state, "a": bad})) != d1

    def test_expanded_divergences_families(self):
        # a clean expand_canonical layout: sum carries identity rows, mean is
        # replicated — then skew one shard of each and demand attribution
        val = (np.arange(4) / 8.0).astype(np.float32)
        states = {
            "s": jnp.asarray(np.stack([val] + [np.zeros(4, np.float32)] * 7)),
            "m": jnp.asarray(np.stack([val] * 8)),
        }
        reds = {"s": "sum", "m": "mean"}
        assert expanded_divergences(states, reds) == []
        skewed, info = faults.skew_replica({"m": states["m"]}, shard=5, seed=2)
        found = expanded_divergences({"m": skewed["m"], "s": states["s"]}, reds)
        assert len(found) == 1 and found[0].shard == 5 and found[0].field == "m"

    def test_replica_divergences_clean_on_replicated(self):
        mesh = _mesh(8)
        rep = jax.device_put(
            jnp.arange(4, dtype=jnp.float32), NamedSharding(mesh, P())
        )
        assert replica_divergences({"r": rep}) == []


# ---------------------------------------------------------------------------
# step mode: the metric-attached auditor (chain surface + policies)
# ---------------------------------------------------------------------------


class TestChainAudit:
    def _metric(self, n=3, **kw):
        m = _SumLike(executor=False)
        auditor = m.attach_integrity(**kw)
        for b in _batches(n, seed=21):
            m.update(jnp.asarray(b))
        drain_pipeline(30.0)
        return m, auditor

    def test_clean_audit_ok(self):
        m, auditor = self._metric()
        report = auditor.audit()
        assert report.ok and report.checked >= 1 and report.action == "none"
        assert auditor.stats["captures"] == 3 and auditor.baseline_count == 3
        assert m.integrity is auditor
        assert float(m.compute()) == float(np.sum(np.concatenate(_batches(3, seed=21))))

    def test_bit_flip_detected_at_read_within_one_interval(self):
        """The acceptance property: a 1-bit flip between updates is caught at
        the very next read — no extra updates, no explicit audit call."""
        m, auditor = self._metric(on_divergence="raise")
        before = _counter("integrity.divergences")
        info = faults.flip_state_bits(m, seed=4)
        with pytest.raises(StateDivergenceError) as err:
            m.compute()
        assert err.value.surface == "chain"
        assert info["field"] in err.value.field
        assert auditor.stats["divergences"] >= 1
        assert _counter("integrity.divergences") > before

    def test_explicit_audit_raises_flighted(self):
        m, auditor = self._metric(on_divergence="raise")
        faults.flip_state_bits(m, seed=1)
        with pytest.raises(StateDivergenceError):
            auditor.audit()
        crumbs = [
            c for c in obs.dump_diagnostics()["breadcrumbs"]
            if c.get("kind") == "integrity_divergence"
        ]
        assert crumbs and crumbs[-1]["data"]["owner"] == "_SumLike"

    def test_policy_restore_heals_bit_exact(self):
        m, auditor = self._metric(on_divergence="restore")
        want = float(m.compute())
        clean_fp = host_fingerprints({k: np.asarray(v) for k, v in m._copy_state_dict().items()})
        faults.flip_state_bits(m, seed=9)
        got = float(m.compute())  # read-point restore, then the read proceeds
        assert got == want
        assert auditor.stats["restores"] == 1
        healed = host_fingerprints({k: np.asarray(v) for k, v in m._copy_state_dict().items()})
        assert fingerprint_digest(healed) == fingerprint_digest(clean_fp)
        m.update(jnp.asarray([8.0]))  # the run continues on verified bits
        drain_pipeline(30.0)
        assert float(m.compute()) == want + 8.0

    def test_policy_degraded_serves_last_good(self):
        m, auditor = self._metric(on_divergence="degraded")
        want = float(m.compute())  # caches the last-good value
        faults.flip_state_bits(m, seed=2)
        got = m.compute()
        assert isinstance(got, DegradedValue)
        assert float(got.value) == want
        assert auditor.stats["degraded_serves"] == 1

    def test_restore_without_snapshot_escalates_to_raise(self):
        m, _ = self._metric(on_divergence="restore", snapshots=False)
        faults.flip_state_bits(m, seed=3)
        with pytest.raises(StateDivergenceError):
            m.compute()

    def test_async_read_verifies_on_worker_raise(self):
        m, _ = self._metric(on_divergence="raise")
        faults.flip_state_bits(m, seed=5)
        fut = m.compute_async()
        with pytest.raises(StateDivergenceError):
            fut.result(60.0)

    def test_async_read_verifies_on_worker_degraded(self):
        m, _ = self._metric(on_divergence="degraded")
        want = float(m.compute())
        faults.flip_state_bits(m, seed=6)
        got = m.compute_async().result(60.0)
        assert isinstance(got, DegradedValue) and float(got.value) == want

    def test_stale_baseline_still_runs_replica_checks(self):
        m = _SumLike(executor=False)
        auditor = m.attach_integrity(every_n_updates=100)  # cadence never fires
        m.update(jnp.asarray([1.0]))
        report = auditor.audit()
        assert report.ok and report.checked == 0  # no baseline yet: nothing chained
        assert auditor.stats["audits"] == 1

    def test_detach_and_pickle_drop_auditor(self):
        import pickle

        m, auditor = self._metric()
        blob = pickle.dumps(m)
        m2 = pickle.loads(blob)
        assert m2.integrity is None
        auditor.detach()
        assert m.integrity is None
        faults.flip_state_bits(m, seed=7)
        m.compute()  # detached: the read no longer audits

    def test_policy_validation(self):
        with pytest.raises(ValueError, match="on_divergence"):
            IntegrityAuditor(_SumLike(executor=False), on_divergence="bogus")
        with pytest.raises(ValueError, match="every_n_updates"):
            IntegrityAuditor(_SumLike(executor=False), every_n_updates=0)
        with pytest.raises(ValueError, match="on_divergence"):
            DeferredIntegrity(object(), on_divergence="explode")


# ---------------------------------------------------------------------------
# deferred mode: per-shard audits of the carried states
# ---------------------------------------------------------------------------


class TestDeferredAudit:
    def _step(self, on_divergence="raise", shadow=False):
        coll = MetricCollection({"m": _SumLike(executor=False)}, compute_groups=False)
        step = make_deferred_collection_step(coll, _mesh(8), axis_name="batch")
        if shadow:
            step.attach_shadow(every_n_steps=1, on_shard_loss="raise")
        integ = step.attach_integrity(every_n_steps=1, on_divergence=on_divergence)
        return step, integ

    def _run(self, step, batches):
        mesh = _mesh(8)
        st = step.init_states()
        for b in batches:
            st = step.local_step(st, _put(mesh, b))
        drain_pipeline(30.0)
        return st

    def test_clean_audit_and_cadence(self):
        step, integ = self._step()
        st = self._run(step, _batches(3, seed=41))
        assert integ.baseline_steps == step.steps
        report = integ.audit(st)
        assert report.ok and report.checked >= 1
        assert integ.stats["captures"] == 3 and step.integrity is integ

    def test_skewed_replica_named_by_shard(self):
        """1-bit flip in ONE shard row, caught within one audit interval with
        the offending shard named — the deferred half of the acceptance."""
        step, integ = self._step(on_divergence="raise")
        st = self._run(step, _batches(3, seed=42))
        skewed, info = faults.skew_replica(st, shard=3, seed=1)
        with pytest.raises(StateDivergenceError) as err:
            integ.audit(skewed)
        assert err.value.surface == "chain" and err.value.shard == info["shard"] == 3

    def test_flip_any_leaf_detected(self):
        step, integ = self._step(on_divergence="degraded")
        st = self._run(step, _batches(2, seed=43))
        flipped, _ = faults.flip_state_bits(st, seed=2)
        report = integ.audit(flipped)
        assert not report.ok and report.action == "degraded"
        assert integ.stats["divergences"] >= 1

    def test_restore_converges_bit_exact(self):
        step, integ = self._step(on_divergence="restore", shadow=True)
        st = self._run(step, _batches(4, seed=44))
        clean = step.reduce(st)
        skewed, _ = faults.skew_replica(st, shard=2, seed=3)
        report = integ.audit(skewed)
        assert not report.ok and report.action == "restored"
        assert report.restored_states is not None
        healed = step.reduce(report.restored_states)
        np.testing.assert_array_equal(np.asarray(healed["m"]), np.asarray(clean["m"]))
        assert integ.stats["restores"] == 1
        # the loop continues on the restored carry
        mesh = _mesh(8)
        extra = _batches(1, seed=45)[0]
        st2 = step.local_step(report.restored_states, _put(mesh, extra))
        np.testing.assert_array_equal(
            np.asarray(step.reduce(st2)["m"]),
            np.asarray(clean["m"]) + np.float32(extra.sum()),
        )

    def test_restore_without_shadow_raises(self):
        step, integ = self._step(on_divergence="restore", shadow=False)
        st = self._run(step, _batches(2, seed=46))
        skewed, _ = faults.skew_replica(st, shard=1, seed=4)
        with pytest.raises(StateDivergenceError):
            integ.audit(skewed)


# ---------------------------------------------------------------------------
# mirror coherence: diverged recovery mirrors rebuild, never serve
# ---------------------------------------------------------------------------


class TestMirrorCoherence:
    def test_lane_mirror_divergence_invalidates(self):
        state = {"hits": jnp.asarray(np.arange(8, dtype=np.float32))}
        mirror = LaneStateMirror()
        mirror.snapshot(state, np.asarray([0, 1]), update_count=1, capacity=8)
        assert mirror.verify(state, 1)  # coherent
        before = _counter("integrity.mirror_rebuilds")
        mirror._mirror["hits"], _ = faults._flip_bits_host(mirror._mirror["hits"], 1, 0)
        assert not mirror.verify(state, 1)
        assert mirror._mirror is None  # invalidated: next snapshot rebuilds
        assert _counter("integrity.mirror_rebuilds") > before
        mirror.snapshot(state, np.asarray([0]), update_count=2, capacity=8)
        assert mirror.stats["rebuilds"] >= 1 and mirror.verify(state, 2)

    def test_lane_mirror_out_of_phase_is_not_audited(self):
        state = {"hits": jnp.asarray(np.ones(4, np.float32))}
        mirror = LaneStateMirror()
        mirror.snapshot(state, np.asarray([0]), update_count=1, capacity=4)
        assert mirror.verify(state, 2)  # count moved: nothing coherent to audit

    def test_class_mirror_divergence_invalidates(self):
        state = {"confmat": jnp.asarray(np.arange(12, dtype=np.int32).reshape(3, 4))}
        mirror = ClassShardMirror()
        mirror.snapshot(state, {"confmat": np.asarray([0, 5], np.int64)}, update_count=1)
        assert mirror.verify(state, 1)
        mirror._mirror["confmat"], _ = faults._flip_bits_host(mirror._mirror["confmat"], 1, 1)
        assert not mirror.verify(state, 1)
        assert mirror._mirror is None

    def test_auditor_heals_attached_mirror(self):
        m = _SumLike(executor=False)
        auditor = m.attach_integrity()
        m.update(jnp.asarray([1.0]))
        drain_pipeline(30.0)
        mirror = LaneStateMirror()
        state = {k: jnp.asarray(v) for k, v in m._copy_state_dict().items() if k == "total"}
        mirror.snapshot(state, np.asarray([], np.int64), update_count=1, capacity=1)
        m.__dict__["_lane_mirror"] = mirror
        mirror._mirror["total"], _ = faults._flip_bits_host(mirror._mirror["total"], 1, 0)
        report = auditor.audit()  # mirror surface self-heals; chain stays ok
        assert report.ok
        assert auditor.stats.get("mirror_rebuilds", 0) == 1
        assert mirror._mirror is None
        del m.__dict__["_lane_mirror"]


# ---------------------------------------------------------------------------
# verified recovery: manifest fingerprints + installed-state verification
# ---------------------------------------------------------------------------


class TestVerifiedRestore:
    def test_manifest_carries_fingerprints(self, tmp_path):
        m = _SumLike(executor=False)
        m.update(jnp.asarray(_batches(1, seed=51)[0]))
        path = str(tmp_path / "snap.ckpt")
        save_state(m, path)
        leaves = load_manifest(path)["leaves"]
        with_fp = [e for e in leaves if e.get("fingerprint")]
        assert with_fp, "manifest leaves carry pre-save fingerprints"
        for e in with_fp:
            assert len(e["fingerprint"]) == 2
            assert all(0 <= w < 2**32 for w in e["fingerprint"])

    def test_clean_restore_verifies_and_passes(self, tmp_path):
        m = _SumLike(executor=False)
        for b in _batches(2, seed=52):
            m.update(jnp.asarray(b))
        path = str(tmp_path / "snap.ckpt")
        save_state(m, path)
        m2 = _SumLike(executor=False)
        restore_state(path, m2)
        assert float(m2.compute()) == float(m.compute())

    def _corrupting_load(self, cls, monkeypatch, only_first=True):
        """Patch ``load_state`` to flip one bit during install — the
        H2D/aliasing corruption the post-install verification exists for."""
        orig = cls.load_state
        calls = {"n": 0}

        def bad_load(self, state, **kw):
            calls["n"] += 1
            if calls["n"] == 1 or not only_first:
                state = dict(state)
                bad, _ = faults._flip_bits_host(np.asarray(state["total"]), 1, 13)
                state["total"] = bad
            return orig(self, state, **kw)

        monkeypatch.setattr(cls, "load_state", bad_load)
        return calls

    def test_install_corruption_detected(self, tmp_path, monkeypatch):
        m = _SumLike(executor=False)
        m.update(jnp.asarray(_batches(1, seed=53)[0]))
        path = str(tmp_path / "snap.ckpt")
        save_state(m, path)
        before = _counter("checkpoint.integrity_mismatches")
        m2 = _SumLike(executor=False)
        self._corrupting_load(_SumLike, monkeypatch)
        with pytest.raises(StateDivergenceError) as err:
            restore_state(path, m2)
        assert err.value.surface == "restore" and "total" in str(err.value.field)
        assert isinstance(err.value, StateCorruptionError)  # rotation-scan compatible
        assert _counter("checkpoint.integrity_mismatches") > before

    def test_rotation_falls_back_past_install_mismatch(self, tmp_path, monkeypatch):
        """An installed-state fingerprint mismatch is treated exactly like a
        torn file: breadcrumb, counter, fall back to the next-older snapshot."""
        store = str(tmp_path / "store")
        m = _SumLike(executor=False)
        checkpoints = []
        for b in _batches(3, seed=54):
            m.update(jnp.asarray(b))
            save_state(m, store, keep=3)
            checkpoints.append(float(m.compute()))
        m2 = _SumLike(executor=False)
        self._corrupting_load(_SumLike, monkeypatch)  # newest install corrupts
        warned = []
        info = restore_state(store, m2, on_fallback=lambda p, e: warned.append((p, e)))
        assert info["fallbacks_skipped"] == 1 and len(warned) == 1
        assert isinstance(warned[0][1], StateDivergenceError)
        assert float(m2.compute()) == checkpoints[1]  # newest VERIFIED, not newest


# ---------------------------------------------------------------------------
# fleet surface: ship-time checksums, corrupt-delta drop + quarantine + heal
# ---------------------------------------------------------------------------

FLEET_REDS = {"total": "sum", "n": "sum"}


class _Leaf:
    """One simulated leaf; draws multiples of 1/8 so fp32 sums are exact."""

    def __init__(self, seed):
        self.rng = np.random.RandomState(seed)
        self.state = {
            "total": np.zeros(4, np.float32),
            "n": np.asarray(0, np.int64),
        }
        self.updates = 0

    def update(self):
        x = (self.rng.randint(-40, 40, 4) / 8.0).astype(np.float32)
        self.state["total"] = self.state["total"] + x
        self.state["n"] = self.state["n"] + 1
        self.updates += 1

    def source(self):
        return lambda: (dict(self.state), dict(FLEET_REDS), self.updates)


class TestFleetChecksum:
    def test_payload_checksum_deterministic_and_sensitive(self):
        payload = {"total": np.arange(4, dtype=np.float32), "n": np.asarray(3, np.int64)}
        c1 = payload_checksum(payload)
        c2 = payload_checksum({k: np.array(v) for k, v in payload.items()})
        assert c1 == c2 and len(c1) == 64
        bad, _ = faults._flip_bits_host(payload["total"], 1, 0)
        assert payload_checksum({**payload, "total": bad}) != c1

    def test_exports_are_stamped(self):
        leaf = _Leaf(1)
        exporter = LeafExporter(
            "leaf/0", leaf.source(), Uplink({}, sleep=NO_SLEEP), "agg/root", outbox_limit=64
        )
        leaf.update()
        delta = exporter.export()
        assert delta.checksum == payload_checksum(delta.payload)

    def test_ledger_drops_corrupt_delta_and_heals_on_full(self):
        import copy
        import dataclasses

        leaf = _Leaf(2)
        exporter = LeafExporter(
            "leaf/0", leaf.source(), Uplink({}, sleep=NO_SLEEP), "agg/root", outbox_limit=64
        )
        leaf.update()
        clean = exporter.export()  # epoch 1, kind="full"
        bad_payload = copy.deepcopy(clean.payload)
        assert any(
            isinstance(v, np.ndarray) and v.size for v in jax.tree_util.tree_leaves(bad_payload)
        )
        for v in jax.tree_util.tree_leaves(bad_payload):
            if isinstance(v, np.ndarray) and v.size:
                v.reshape(-1).view(np.uint8)[0] ^= np.uint8(1)
                break
        corrupt = dataclasses.replace(clean, payload=bad_payload)
        ledger = LeafLedger("leaf/0", watermark=8)
        before = _counter("fleet.deltas_corrupt")
        ack = ledger.offer(corrupt)
        assert ack["needs_full"] and ack["applied_epoch"] == 0
        assert ledger.quarantined and ledger.stats["corrupt_dropped"] == 1
        assert _counter("fleet.deltas_corrupt") > before
        # the re-shipped CLEAN full resync heals the quarantine
        ack2 = ledger.offer(clean)
        assert ack2["applied_epoch"] == 1 and not ledger.quarantined

    def test_corrupt_delta_converges_bit_exact_after_resync(self):
        """End-to-end acceptance: a delta corrupted in flight never merges;
        the quarantine → full-resync cycle converges the global view onto the
        exact fault-free state."""
        topo = FleetTopology(["leaf/0", "leaf/1"])
        fleet = build_fleet(topo, sleep=NO_SLEEP)
        leaves = {lid: _Leaf(10 + i) for i, lid in enumerate(topo.leaves)}
        exporters = {lid: fleet.leaf_exporter(lid, leaves[lid].source()) for lid in topo.leaves}
        with faults.corrupt_delta_payload("leaf/0", n=1) as injected:
            for lid in topo.leaves:
                leaves[lid].update()
                exporters[lid].ship(wait=True)
        assert injected["corrupted"] == 1
        assert exporters["leaf/0"].stats["resyncs_requested"] == 1
        for _ in range(2):  # the resync + one steady round
            for lid in topo.leaves:
                leaves[lid].update()
                exporters[lid].ship(wait=True)
        view = fleet.view()
        assert view.healthy() and view.coverage() == 1.0
        got = view.read()
        assert not isinstance(got, DegradedValue)
        want_total = leaves["leaf/0"].state["total"] + leaves["leaf/1"].state["total"]
        np.testing.assert_array_equal(np.asarray(got["total"], np.float32), want_total)
        assert int(np.asarray(got["n"])) == sum(l.updates for l in leaves.values())
