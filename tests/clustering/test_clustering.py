"""Clustering metric parity tests vs sklearn."""
import sys

import numpy as np
import pytest
from sklearn.metrics import (
    adjusted_mutual_info_score as sk_ami,
    adjusted_rand_score as sk_ari,
    calinski_harabasz_score as sk_ch,
    completeness_score as sk_completeness,
    davies_bouldin_score as sk_db,
    fowlkes_mallows_score as sk_fmi,
    homogeneity_score as sk_homogeneity,
    mutual_info_score as sk_mi,
    normalized_mutual_info_score as sk_nmi,
    rand_score as sk_rand,
    v_measure_score as sk_vm,
)

sys.path.insert(0, "/root/repo/tests")

import torchmetrics_tpu as tm  # noqa: E402
import torchmetrics_tpu.functional as F  # noqa: E402

rng = np.random.RandomState(31)
N = 120
PREDS = rng.randint(0, 6, N)
TARGET = rng.randint(0, 5, N)
DATA = rng.randn(N, 4).astype(np.float32)
LABELS = rng.randint(0, 4, N)

LABEL_CASES = [
    (F.mutual_info_score, tm.MutualInfoScore, sk_mi, {}),
    (F.rand_score, tm.RandScore, sk_rand, {}),
    (F.adjusted_rand_score, tm.AdjustedRandScore, sk_ari, {}),
    (F.fowlkes_mallows_index, tm.FowlkesMallowsIndex, sk_fmi, {}),
    (F.homogeneity_score, tm.HomogeneityScore, sk_homogeneity, {}),
    (F.completeness_score, tm.CompletenessScore, sk_completeness, {}),
    (F.v_measure_score, tm.VMeasureScore, sk_vm, {}),
    (F.normalized_mutual_info_score, tm.NormalizedMutualInfoScore, sk_nmi, {}),
    (F.adjusted_mutual_info_score, tm.AdjustedMutualInfoScore, sk_ami, {}),
]


@pytest.mark.parametrize("fn,cls,sk,kw", LABEL_CASES, ids=[c[1].__name__ for c in LABEL_CASES])
def test_label_metrics(fn, cls, sk, kw):
    got = float(fn(PREDS, TARGET, **kw))
    want = float(sk(TARGET, PREDS))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    m = cls(**kw)
    m.update(PREDS[:60], TARGET[:60])
    m.update(PREDS[60:], TARGET[60:])
    np.testing.assert_allclose(float(m.compute()), want, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("average_method,sk_name", [("min", "min"), ("geometric", "geometric"), ("max", "max")])
def test_nmi_average_methods(average_method, sk_name):
    got = float(F.normalized_mutual_info_score(PREDS, TARGET, average_method))
    want = float(sk_nmi(TARGET, PREDS, average_method=sk_name))
    np.testing.assert_allclose(got, want, atol=1e-4)
    got_ami = float(F.adjusted_mutual_info_score(PREDS, TARGET, average_method))
    want_ami = float(sk_ami(TARGET, PREDS, average_method=sk_name))
    np.testing.assert_allclose(got_ami, want_ami, atol=1e-4)


def test_perfect_and_permuted():
    assert float(F.rand_score(PREDS, PREDS)) == 1.0
    assert float(F.adjusted_rand_score(PREDS, PREDS)) == 1.0
    # label permutation leaves scores invariant
    perm = rng.permutation(6)
    np.testing.assert_allclose(
        float(F.mutual_info_score(perm[PREDS], TARGET)), float(F.mutual_info_score(PREDS, TARGET)), atol=1e-5
    )


def test_intrinsic_metrics():
    np.testing.assert_allclose(float(F.calinski_harabasz_score(DATA, LABELS)), sk_ch(DATA, LABELS), rtol=1e-4)
    np.testing.assert_allclose(float(F.davies_bouldin_score(DATA, LABELS)), sk_db(DATA, LABELS), rtol=1e-4)

    m = tm.CalinskiHarabaszScore()
    m.update(DATA[:60], LABELS[:60])
    m.update(DATA[60:], LABELS[60:])
    np.testing.assert_allclose(float(m.compute()), sk_ch(DATA, LABELS), rtol=1e-4)

    m = tm.DaviesBouldinScore()
    m.update(DATA, LABELS)
    np.testing.assert_allclose(float(m.compute()), sk_db(DATA, LABELS), rtol=1e-4)


def test_dunn_index():
    # well separated clusters -> dunn via independent numpy computation
    data = np.concatenate([rng.randn(20, 3) * 0.1 + c for c in (0, 5, 10)]).astype(np.float32)
    labels = np.repeat([0, 1, 2], 20)
    got = float(F.dunn_index(data, labels))

    centroids = np.stack([data[labels == k].mean(0) for k in range(3)])
    inter = min(
        np.linalg.norm(centroids[i] - centroids[j]) for i in range(3) for j in range(3) if i < j
    )
    intra = max(np.linalg.norm(data[labels == k] - centroids[k], axis=1).max() for k in range(3))
    np.testing.assert_allclose(got, inter / intra, rtol=1e-4)

    m = tm.DunnIndex()
    m.update(data, labels)
    np.testing.assert_allclose(float(m.compute()), inter / intra, rtol=1e-4)


def test_validation():
    with pytest.raises(ValueError, match="Expected 2D data"):
        F.calinski_harabasz_score(DATA[:, 0], LABELS)
    with pytest.raises(ValueError, match="real, discrete"):
        F.mutual_info_score(PREDS.astype(np.float32), TARGET)
    with pytest.raises(ValueError, match="average_method"):
        F.normalized_mutual_info_score(PREDS, TARGET, "harmonic")


def test_single_cluster_degenerate_follows_reference():
    """Identical single-cluster labelings: sklearn special-cases this to 1.0,
    but the reference torchmetrics returns 0.0 (zero entropy -> zero NMI/AMI
    without the special case) — we pin the REFERENCE behavior, which is the
    parity target."""
    same = np.zeros(30, dtype=int)
    assert float(F.normalized_mutual_info_score(same, same)) == 0.0
    assert float(F.adjusted_mutual_info_score(same, same)) == 0.0
    assert float(sk_nmi(same, same)) == 1.0  # documents the sklearn difference
