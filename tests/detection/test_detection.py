"""Detection metric parity tests.

Oracles: the reference's pure-torch code where usable (IoU modular classes,
panoptic quality, legacy _mean_ap) with a shimmed torchvision providing the
standard box formulas.
"""
import sys

import numpy as np
import pytest
import torch

sys.path.insert(0, "/root/repo/tests")
sys.path.insert(0, "/root/repo/tests/detection")
from helpers.reference import load_reference_torchmetrics  # noqa: E402
import torchvision_shim  # noqa: E402

ref_tm = load_reference_torchmetrics()
torchvision_shim.install()

# reference PQ gates on a torch>=1.12 flag that the RequirementCache shim zeroes out
import torchmetrics.detection.panoptic_qualities as _ref_pq_mod  # noqa: E402
import torchmetrics.functional.detection._panoptic_quality_common as _ref_pq_common  # noqa: E402
import torchmetrics.functional.detection.panoptic_qualities as _ref_pq_func  # noqa: E402

for _m in (_ref_pq_mod, _ref_pq_common, _ref_pq_func):
    if hasattr(_m, "_TORCH_GREATER_EQUAL_1_12"):
        _m._TORCH_GREATER_EQUAL_1_12 = True

import torchmetrics_tpu as tm  # noqa: E402
import torchmetrics_tpu.functional as F  # noqa: E402

rng = np.random.RandomState(5)


def _rand_boxes(n, size=200.0):
    xy = rng.rand(n, 2).astype(np.float32) * size
    wh = (rng.rand(n, 2).astype(np.float32) * 60 + 2)
    return np.concatenate([xy, xy + wh], axis=1)


class TestIoUFunctional:
    @pytest.mark.parametrize(
        "ours,shim",
        [
            (F.intersection_over_union, torchvision_shim.box_iou),
            (F.generalized_intersection_over_union, torchvision_shim.generalized_box_iou),
            (F.distance_intersection_over_union, torchvision_shim.distance_box_iou),
            (F.complete_intersection_over_union, torchvision_shim.complete_box_iou),
        ],
    )
    def test_pairwise_matrix(self, ours, shim):
        a, b = _rand_boxes(8), _rand_boxes(6)
        got = np.asarray(ours(a, b, aggregate=False))
        want = shim(torch.from_numpy(a), torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_aggregate_and_threshold(self):
        a, b = _rand_boxes(5), _rand_boxes(5)
        got = np.asarray(F.intersection_over_union(a, b, iou_threshold=0.3))
        iou = torchvision_shim.box_iou(torch.from_numpy(a), torch.from_numpy(b))
        iou[iou < 0.3] = 0
        np.testing.assert_allclose(got, iou.diag().mean().numpy(), atol=1e-4)

    def test_reference_docstring_value(self):
        # anchor to the reference's own documented example (functional/detection/iou.py)
        preds = np.asarray(
            [[296.55, 93.96, 314.97, 152.79], [328.94, 97.05, 342.49, 122.98], [356.62, 95.47, 372.33, 147.55]],
            dtype=np.float32,
        )
        target = np.asarray(
            [[300.00, 100.00, 315.00, 150.00], [330.00, 100.00, 350.00, 125.00], [350.00, 100.00, 375.00, 150.00]],
            dtype=np.float32,
        )
        np.testing.assert_allclose(float(F.intersection_over_union(preds, target)), 0.5879, atol=1e-4)
        np.testing.assert_allclose(float(F.generalized_intersection_over_union(preds, target)), 0.5638, atol=1e-2)


class TestIoUModular:
    def _inputs(self, n_img=4):
        preds, target = [], []
        for _ in range(n_img):
            n_d, n_g = rng.randint(1, 6), rng.randint(1, 6)
            preds.append(
                {"boxes": _rand_boxes(n_d), "labels": rng.randint(0, 3, n_d), "scores": rng.rand(n_d).astype(np.float32)}
            )
            target.append({"boxes": _rand_boxes(n_g), "labels": rng.randint(0, 3, n_g)})
        return preds, target

    @pytest.mark.parametrize("cls_name,mod_name", [
        ("IntersectionOverUnion", "iou"), ("GeneralizedIntersectionOverUnion", "giou"),
        ("DistanceIntersectionOverUnion", "diou"), ("CompleteIntersectionOverUnion", "ciou"),
    ])
    @pytest.mark.parametrize("respect_labels", [True, False])
    def test_parity(self, cls_name, mod_name, respect_labels):
        import importlib

        # reference classes gate on torchvision flags; force them on (shim installed)
        ref_mod = importlib.import_module(f"torchmetrics.detection.{mod_name}")
        for m_name in (
            f"torchmetrics.detection.{mod_name}",
            f"torchmetrics.functional.detection.{mod_name}",
        ):
            m = importlib.import_module(m_name)
            for flag in ("_TORCHVISION_GREATER_EQUAL_0_8", "_TORCHVISION_GREATER_EQUAL_0_13"):
                if hasattr(m, flag):
                    setattr(m, flag, True)

        preds, target = self._inputs()
        ours = getattr(tm, cls_name)(respect_labels=respect_labels, class_metrics=True)
        ref = getattr(ref_mod, cls_name)(respect_labels=respect_labels, class_metrics=True)
        ours.update(preds, target)
        ref.update(
            [{k: torch.from_numpy(np.asarray(v)) for k, v in p.items()} for p in preds],
            [{k: torch.from_numpy(np.asarray(v)) for k, v in t.items()} for t in target],
        )
        got = ours.compute()
        want = ref.compute()
        for k in want:
            np.testing.assert_allclose(np.asarray(got[k]), want[k].numpy(), atol=1e-3, err_msg=k)


class TestPanopticQuality:
    def _inputs(self):
        # (B, H, W, 2) category/instance maps
        b, h, w = 2, 12, 12
        cats = np.array([0, 1, 6, 7])
        preds = np.stack(
            [cats[rng.randint(0, 4, (h, w))], rng.randint(0, 3, (h, w))], axis=-1
        )
        preds = np.stack([preds, np.stack([cats[rng.randint(0, 4, (h, w))], rng.randint(0, 3, (h, w))], axis=-1)])
        target = preds.copy()
        # perturb some pixels
        m = rng.rand(b, h, w) < 0.25
        target[m] = np.stack([cats[rng.randint(0, 4, m.sum())], rng.randint(0, 3, m.sum())], axis=-1)
        return preds, target

    @pytest.mark.parametrize("return_sq_and_rq", [False, True])
    @pytest.mark.parametrize("return_per_class", [False, True])
    def test_parity(self, return_sq_and_rq, return_per_class):
        preds, target = self._inputs()
        kw = {"things": {0, 1}, "stuffs": {6, 7}, "return_sq_and_rq": return_sq_and_rq, "return_per_class": return_per_class}
        ours = tm.PanopticQuality(**kw)
        ref = ref_tm.detection.PanopticQuality(**kw)
        ours.update(preds, target)
        ref.update(torch.from_numpy(preds), torch.from_numpy(target))
        np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5)

    def test_modified_pq(self):
        preds, target = self._inputs()
        ours = tm.ModifiedPanopticQuality(things={0, 1}, stuffs={6, 7})
        ref = ref_tm.detection.ModifiedPanopticQuality(things={0, 1}, stuffs={6, 7})
        ours.update(preds, target)
        ref.update(torch.from_numpy(preds), torch.from_numpy(target))
        np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5)

    def test_functional(self):
        preds, target = self._inputs()
        got = F.panoptic_quality(preds, target, things={0, 1}, stuffs={6, 7})
        want = ref_tm.functional.detection.panoptic_quality(
            torch.from_numpy(preds), torch.from_numpy(target), things={0, 1}, stuffs={6, 7}
        )
        np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-5)

    def test_validation(self):
        with pytest.raises(ValueError, match="distinct"):
            tm.PanopticQuality(things={0, 1}, stuffs={1, 2})
        m = tm.PanopticQuality(things={0}, stuffs={1})
        with pytest.raises(ValueError, match="Unknown categories"):
            m.update(np.full((1, 4, 4, 2), 9), np.zeros((1, 4, 4, 2), dtype=int))


class TestMeanAveragePrecision:
    def _inputs(self, n_img=6, seed=17):
        r = np.random.RandomState(seed)

        def boxes(n):
            xy = r.rand(n, 2).astype(np.float32) * 150
            wh = r.rand(n, 2).astype(np.float32) * 80 + 4
            return np.concatenate([xy, xy + wh], axis=1)

        preds, target = [], []
        for _ in range(n_img):
            n_g = r.randint(1, 7)
            gt = boxes(n_g)
            gt_labels = r.randint(0, 4, n_g)
            # detections: jittered gts + noise boxes
            keep = r.rand(n_g) > 0.25
            det = gt[keep] + r.randn(keep.sum(), 4).astype(np.float32) * 6
            det_labels = gt_labels[keep].copy()
            flip = r.rand(len(det_labels)) < 0.2
            det_labels[flip] = r.randint(0, 4, flip.sum())
            extra = boxes(r.randint(0, 4))
            det = np.concatenate([det, extra]) if len(extra) else det
            det_labels = np.concatenate([det_labels, r.randint(0, 4, len(extra))])
            scores = r.rand(len(det)).astype(np.float32)
            preds.append({"boxes": det.astype(np.float32), "scores": scores, "labels": det_labels})
            target.append({"boxes": gt, "labels": gt_labels})
        return preds, target

    @staticmethod
    def _to_torch(batch):
        """numpy detection dicts -> the torch layout the legacy oracle takes."""
        return [{k: torch.from_numpy(np.asarray(v)) for k, v in item.items()} for item in batch]

    def _legacy_oracle(self, class_metrics=False):
        import torchmetrics.detection._mean_ap as legacy

        legacy._TORCHVISION_GREATER_EQUAL_0_8 = True
        legacy._PYCOCOTOOLS_AVAILABLE = True  # only guards __init__; bbox path never imports it
        return legacy.MeanAveragePrecision(class_metrics=class_metrics)

    @pytest.mark.parametrize("class_metrics", [False, True])
    def test_parity_vs_legacy(self, class_metrics):
        preds, target = self._inputs()
        ours = tm.MeanAveragePrecision(class_metrics=class_metrics)
        ref = self._legacy_oracle(class_metrics=class_metrics)
        half = len(preds) // 2
        ours.update(preds[:half], target[:half])
        ours.update(preds[half:], target[half:])
        ref.update(
            self._to_torch(preds), self._to_torch(target),
        )
        got = ours.compute()
        want = ref.compute()
        for k in ("map", "map_50", "map_75", "map_small", "map_medium", "map_large",
                  "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large"):
            np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-5, err_msg=k)
        if class_metrics:
            np.testing.assert_allclose(
                np.asarray(got["map_per_class"]), want["map_per_class"].numpy(), atol=1e-5
            )
            np.testing.assert_allclose(
                np.asarray(got["mar_100_per_class"]), want["mar_100_per_class"].numpy(), atol=1e-5
            )
        np.testing.assert_array_equal(np.asarray(got["classes"]), want["classes"].numpy())

    @pytest.mark.parametrize("iou_thresholds", [None, [0.5], [0.3, 0.55, 0.8]])
    @pytest.mark.parametrize("box_format", ["xyxy", "xywh", "cxcywh"])
    def test_parity_thresholds_and_format_grid(self, iou_thresholds, box_format):
        """Legacy-oracle grid over iou_thresholds x box_format (reference
        detection/_mean_ap.py accepts the same axes)."""

        def conv(b):
            if box_format == "xyxy":
                return b
            wh = b[:, 2:] - b[:, :2]
            if box_format == "xywh":
                return np.concatenate([b[:, :2], wh], axis=1)
            return np.concatenate([b[:, :2] + wh / 2, wh], axis=1)  # cxcywh

        preds, target = self._inputs(n_img=4)
        preds = [{**p, "boxes": conv(p["boxes"])} for p in preds]
        target = [{**t, "boxes": conv(t["boxes"])} for t in target]
        ours = tm.MeanAveragePrecision(box_format=box_format, iou_thresholds=iou_thresholds)
        ref = self._legacy_oracle()
        ref.box_format = box_format
        if iou_thresholds is not None:
            ref.iou_thresholds = list(iou_thresholds)
        ours.update(preds, target)
        ref.update(
            self._to_torch(preds), self._to_torch(target),
        )
        got, want = ours.compute(), ref.compute()
        for k in ("map", "map_50", "map_75", "mar_1", "mar_10", "mar_100"):
            np.testing.assert_allclose(
                float(got[k]), float(want[k]), atol=1e-5, err_msg=f"{k} {box_format} {iou_thresholds}"
            )

    @pytest.mark.parametrize("rec_thresholds", [None, [0.0, 0.25, 0.5, 0.75, 1.0]])
    @pytest.mark.parametrize("max_detection_thresholds", [None, [2, 5, 8]])
    def test_parity_rec_and_maxdet_grid(self, rec_thresholds, max_detection_thresholds):
        """Legacy-oracle grid over the remaining reference axes:
        rec_thresholds (PR interpolation grid) x max_detection_thresholds."""
        preds, target = self._inputs(n_img=4)
        ours = tm.MeanAveragePrecision(
            rec_thresholds=rec_thresholds, max_detection_thresholds=max_detection_thresholds
        )
        ref = self._legacy_oracle()
        if rec_thresholds is not None:
            ref.rec_thresholds = list(rec_thresholds)
        if max_detection_thresholds is not None:
            ref.max_detection_thresholds = sorted(max_detection_thresholds)
        ours.update(preds, target)
        ref.update(
            self._to_torch(preds), self._to_torch(target),
        )
        got, want = ours.compute(), ref.compute()
        mds = sorted(max_detection_thresholds or [1, 10, 100])
        keys = ["map", "map_50", "map_75"] + [f"mar_{d}" for d in mds]
        for k in keys:
            # every expected key must exist on BOTH sides — a naming mismatch
            # must fail loudly, not silently skip the axis under test
            assert k in got and k in want, f"missing key {k}: got={sorted(got)}, want={sorted(want.keys())}"
            np.testing.assert_allclose(
                float(got[k]), float(want[k]), atol=1e-5,
                err_msg=f"{k} rec={rec_thresholds} maxdet={max_detection_thresholds}",
            )

    def test_empty_preds(self):
        preds = [{"boxes": np.zeros((0, 4), np.float32), "scores": np.zeros(0, np.float32), "labels": np.zeros(0, np.int64)}]
        target = [{"boxes": _rand_boxes(3), "labels": np.asarray([0, 1, 1])}]
        m = tm.MeanAveragePrecision()
        m.update(preds, target)
        res = m.compute()
        assert float(res["map"]) == 0.0

    def test_perfect_detection(self):
        gt = _rand_boxes(4)
        labels = np.asarray([0, 1, 2, 3])
        preds = [{"boxes": gt, "scores": np.ones(4, np.float32), "labels": labels}]
        target = [{"boxes": gt, "labels": labels}]
        m = tm.MeanAveragePrecision()
        m.update(preds, target)
        assert float(m.compute()["map"]) > 0.99

    def test_crowd_absorbs_detections(self):
        # a det covering a crowd gt must be ignored, not counted as FP
        gt = _rand_boxes(2)
        preds = [{"boxes": gt, "scores": np.asarray([0.95, 0.9], np.float32), "labels": np.asarray([0, 0])}]
        target = [{"boxes": gt, "labels": np.asarray([0, 0]), "iscrowd": np.asarray([1, 0])}]
        m = tm.MeanAveragePrecision()
        m.update(preds, target)
        assert float(m.compute()["map"]) > 0.99

    def test_segm_iou_type(self):
        h = w = 24
        masks_gt = np.zeros((2, h, w), bool)
        masks_gt[0, 2:10, 2:10] = True
        masks_gt[1, 12:20, 12:22] = True
        masks_dt = np.zeros((2, h, w), bool)
        masks_dt[0, 3:10, 2:10] = True
        masks_dt[1, 12:21, 12:22] = True
        preds = [{"masks": masks_dt, "scores": np.asarray([0.9, 0.8], np.float32), "labels": np.asarray([0, 1])}]
        target = [{"masks": masks_gt, "labels": np.asarray([0, 1])}]
        m = tm.MeanAveragePrecision(iou_type="segm")
        m.update(preds, target)
        assert float(m.compute()["map"]) > 0.5
