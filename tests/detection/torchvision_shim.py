"""Minimal torch-based box-op shim so the reference's pure-torch detection code
can serve as a test oracle (torchvision is not installed in this image).

These are independent textbook implementations of the standard box formulas,
used ONLY as the oracle for comparison.
"""
import sys
import types

import torch


def box_area(boxes):
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _inter_union(boxes1, boxes2):
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = torch.max(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.min(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return inter, union


def box_iou(boxes1, boxes2):
    inter, union = _inter_union(boxes1, boxes2)
    return inter / union


def generalized_box_iou(boxes1, boxes2):
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / union
    lt = torch.min(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.max(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    hull = wh[..., 0] * wh[..., 1]
    return iou - (hull - union) / hull


def distance_box_iou(boxes1, boxes2, eps: float = 1e-7):
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / union
    lt = torch.min(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.max(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    diag = wh[..., 0] ** 2 + wh[..., 1] ** 2 + eps
    c1 = (boxes1[:, :2] + boxes1[:, 2:]) / 2
    c2 = (boxes2[:, :2] + boxes2[:, 2:]) / 2
    d = c1[:, None, :] - c2[None, :, :]
    dist = d[..., 0] ** 2 + d[..., 1] ** 2
    return iou - dist / diag


def complete_box_iou(boxes1, boxes2, eps: float = 1e-7):
    diou = distance_box_iou(boxes1, boxes2, eps)
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / union
    w1 = boxes1[:, 2] - boxes1[:, 0]
    h1 = boxes1[:, 3] - boxes1[:, 1]
    w2 = boxes2[:, 2] - boxes2[:, 0]
    h2 = boxes2[:, 3] - boxes2[:, 1]
    import math

    v = (4 / math.pi**2) * (torch.atan(w2 / h2)[None, :] - torch.atan(w1 / h1)[:, None]) ** 2
    alpha = v / (1 - iou + v + eps)
    return diou - alpha * v


def box_convert(boxes, in_fmt, out_fmt):
    if in_fmt == out_fmt:
        return boxes
    if in_fmt == "xywh":
        x, y, w, h = boxes.unbind(-1)
        boxes = torch.stack([x, y, x + w, y + h], -1)
    elif in_fmt == "cxcywh":
        cx, cy, w, h = boxes.unbind(-1)
        boxes = torch.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    if out_fmt == "xyxy":
        return boxes
    x1, y1, x2, y2 = boxes.unbind(-1)
    if out_fmt == "xywh":
        return torch.stack([x1, y1, x2 - x1, y2 - y1], -1)
    return torch.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], -1)


def install():
    """Register fake `torchvision` (+ inert `pycocotools.mask`) modules."""
    if "torchvision" not in sys.modules:
        tv = types.ModuleType("torchvision")
        ops = types.ModuleType("torchvision.ops")
        for fn in (box_area, box_iou, generalized_box_iou, distance_box_iou, complete_box_iou, box_convert):
            setattr(ops, fn.__name__, fn)
        tv.ops = ops
        tv.__version__ = "0.15.0"
        sys.modules["torchvision"] = tv
        sys.modules["torchvision.ops"] = ops
    if "pycocotools" not in sys.modules:
        # the legacy mAP imports pycocotools.mask unconditionally but only calls
        # it for iou_type="segm", which these tests never use on the oracle
        pc = types.ModuleType("pycocotools")
        mask = types.ModuleType("pycocotools.mask")
        pc.mask = mask
        sys.modules["pycocotools"] = pc
        sys.modules["pycocotools.mask"] = mask
