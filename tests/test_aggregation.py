"""Aggregation metric tests (mirrors reference tests/unittests/bases/test_aggregation.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import CatMetric, MaxMetric, MeanMetric, MinMetric, RunningMean, RunningSum, SumMetric
from torchmetrics_tpu.parallel.sync import shard_map_compat  # noqa: E402


@pytest.mark.parametrize(
    ("metric_cls", "np_fn"),
    [(SumMetric, np.sum), (MaxMetric, np.max), (MinMetric, np.min)],
)
def test_simple_aggregators(metric_cls, np_fn):
    vals = np.random.randn(4, 10).astype(np.float32)
    m = metric_cls()
    for row in vals:
        m.update(jnp.asarray(row))
    np.testing.assert_allclose(np.asarray(m.compute()), np_fn(vals), rtol=1e-5)


def test_cat_metric():
    vals = np.random.randn(4, 10).astype(np.float32)
    m = CatMetric()
    for row in vals:
        m.update(jnp.asarray(row))
    np.testing.assert_allclose(np.asarray(m.compute()), vals.reshape(-1), rtol=1e-6)


def test_mean_metric_weighted():
    m = MeanMetric()
    m.update(jnp.asarray([1.0, 2.0, 3.0]))
    m.update(jnp.asarray(5.0), weight=2.0)
    assert abs(float(m.compute()) - 3.2) < 1e-6


@pytest.mark.parametrize("nan_strategy", ["error", "warn", "ignore", 0.0])
def test_nan_strategies(nan_strategy):
    m = SumMetric(nan_strategy=nan_strategy)
    x = jnp.asarray([1.0, float("nan"), 2.0])
    if nan_strategy == "error":
        with pytest.raises(RuntimeError):
            m.update(x)
    elif nan_strategy == "warn":
        with pytest.warns(UserWarning):
            m.update(x)
        assert float(m.compute()) == 3.0
    else:
        m.update(x)
        assert float(m.compute()) == 3.0


def test_bad_nan_strategy_raises():
    with pytest.raises(ValueError):
        SumMetric(nan_strategy="bogus")


def test_running_mean():
    m = RunningMean(window=3)
    for v in [1.0, 2.0, 3.0, 4.0, 5.0]:
        m.update(v)
    # last 3: 3,4,5
    assert abs(float(m.compute()) - 4.0) < 1e-6


def test_running_sum():
    m = RunningSum(window=2)
    for v in [1.0, 2.0, 3.0]:
        m.update(v)
    assert abs(float(m.compute()) - 5.0) < 1e-6


def test_mean_metric_ddp_semantics(mesh):
    """MeanMetric synced over the mesh equals the global weighted mean."""
    import jax
    from jax.sharding import PartitionSpec as P

    m = MeanMetric()

    def step(x):
        st = m.functional_update(m.init_state(), x)
        st = m.functional_sync(st, "batch")
        return m.functional_compute(st)

    data = jnp.arange(24.0).reshape(8, 3)
    out = jax.jit(shard_map_compat(step, mesh=mesh, in_specs=P("batch"), out_specs=P()))(data)
    assert abs(float(out) - float(data.mean())) < 1e-6
