"""Failure-containment acceptance battery (ISSUE 2).

For every injected fault — NaN batch under a strict policy, a raise mid-update
(after state mutation), a dispatch failure after donation, a hung/broken
multi-host sync, a corrupted restore pytree — the metric's observable state
after the failure must equal its state before the failing call, on both the
eager and executor paths. Plus the satellites: resume-mid-epoch under the
executor (both cross-path directions), the ``functional_sync`` reserved-count
regression, and the recorded executor fallback reasons.
"""
import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import MeanMetric, MetricCollection, SumMetric
from torchmetrics_tpu.parallel.sync import shard_map_compat  # noqa: E402
from torchmetrics_tpu.aggregation import MaxMetric
from torchmetrics_tpu.classification import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MulticlassPrecision,
    MulticlassRecall,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.ops.executor import executor_stats
from torchmetrics_tpu.testing import faults
from torchmetrics_tpu.utils.exceptions import (
    StateCorruptionError,
    SyncTimeoutError,
    TorchMetricsUserWarning,
)

NUM_CLASSES = 5


def _mc_batch(n, seed):
    r = np.random.RandomState(seed)
    return (
        jnp.asarray(r.randn(n, NUM_CLASSES).astype(np.float32)),
        jnp.asarray(r.randint(0, NUM_CLASSES, n)),
    )


def _observable(metric):
    """Host copy of everything the containment contract covers. Forced
    ``np.array`` copies: on CPU a zero-copy device view would be silently
    rewritten by an in-place donating dispatch — the very hazard under test."""
    return (
        {
            k: ([np.array(x) for x in v] if isinstance(v, list) else np.array(v))
            for k, v in ((kk, metric._state[kk]) for kk in metric._defaults)
        },
        metric.update_count,
    )


def _assert_observable_equal(before, after):
    state_b, count_b = before
    state_a, count_a = after
    assert count_b == count_a, f"update_count changed across a failed call: {count_b} -> {count_a}"
    assert set(state_b) == set(state_a)
    for k in state_b:
        b, a = state_b[k], state_a[k]
        if isinstance(b, list):
            assert len(b) == len(a)
            for x, y in zip(b, a):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        else:
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a), err_msg=f"state field {k!r}")


class _TwoPhase(Metric):
    """Two states mutated sequentially — the canonical half-applied hazard."""

    full_state_update = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("first", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("second", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.first = self.first + x.sum()
        self.second = self.second + (x * 2).sum()

    def compute(self):
        return self.first + self.second


# ---------------------------------------------------------------------------
# transactional update / forward (eager + executor)
# ---------------------------------------------------------------------------


class TestTransactionalUpdate:
    @pytest.mark.parametrize("use_executor", [True, False], ids=["executor", "eager"])
    @pytest.mark.parametrize("cls", [SumMetric, MeanMetric, MaxMetric])
    @pytest.mark.parametrize("call", ["update", "forward"])
    def test_nan_batch_strict_policy_rolls_back(self, cls, call, use_executor):
        """nan_strategy='error' raising on a poisoned batch must leave the
        accumulated state exactly as it was — the epoch survives the batch
        (with the executor flag in both positions; 'error' instances
        self-declare untraceable, so both land on the contained eager body)."""
        m = cls(nan_strategy="error", executor=use_executor)
        m.update(jnp.asarray([1.0, 2.0, 3.0]))
        expected = float(m.compute())
        before = _observable(m)
        (bad,) = faults.poison_batch(jnp.asarray([4.0, 5.0]), frac=0.5, seed=3)
        with pytest.raises(RuntimeError, match="nan"):
            getattr(m, call)(bad)
        _assert_observable_equal(before, _observable(m))
        m._computed = None
        assert float(m.compute()) == expected
        m.update(jnp.asarray([4.0]))  # still usable after the contained failure

    @pytest.mark.parametrize("use_executor", [True, False], ids=["executor", "eager"])
    def test_mid_update_raise_after_mutation(self, use_executor):
        """An exception raised AFTER the update body mutated state (the
        half-applied transition) rolls everything back, on both paths."""
        m = _TwoPhase(executor=use_executor)
        m.update(jnp.asarray([1.0, 2.0]))
        before = _observable(m)
        with faults.raise_in_update(m, after_mutation=True):
            with pytest.raises(faults.FaultInjected):
                m.update(jnp.asarray([10.0]))
        _assert_observable_equal(before, _observable(m))
        # the metric keeps working once the fault clears
        m.update(jnp.asarray([3.0]))
        ctrl = _TwoPhase(executor=False)
        ctrl.update(jnp.asarray([1.0, 2.0]))
        ctrl.update(jnp.asarray([3.0]))
        np.testing.assert_allclose(float(m.compute()), float(ctrl.compute()), rtol=1e-6)

    def test_mid_update_raise_records_fallback_reason(self):
        """With the executor on, a body that cannot trace (it raises) gets the
        sticky eager fallback WITH the reason recorded and surfaced."""
        m = _TwoPhase(executor=True)
        with faults.raise_in_update(m, after_mutation=True):
            with pytest.raises(faults.FaultInjected):
                m.update(jnp.asarray([1.0]))
        status = m.executor_status
        assert status["enabled"] is True
        assert status["fallback_reason"] is not None
        assert "FaultInjected" in status["fallback_reason"]

    @pytest.mark.parametrize("use_executor", [True, False], ids=["executor", "eager"])
    def test_compute_raise_leaves_state_intact(self, use_executor):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=use_executor)
        m.update(*_mc_batch(16, 0))
        before = _observable(m)
        with faults.raise_in_compute(m):
            with pytest.raises(faults.FaultInjected):
                m.compute()
        _assert_observable_equal(before, _observable(m))
        assert 0.0 <= float(m.compute()) <= 1.0


class TestForwardContainment:
    def test_full_state_forward_failure_keeps_cached_global_state(self):
        """THE regression this PR exists for: _forward_full_state_update used
        to lose the accumulated global state when the batch-value compute
        raised after the mid-call reset."""
        m = MaxMetric(nan_strategy="ignore", executor=False)  # full_state_update=True
        m.update(jnp.asarray([5.0, 1.0]))
        before = _observable(m)
        with faults.raise_in_compute(m):
            with pytest.raises(faults.FaultInjected):
                m.forward(jnp.asarray([3.0]))
        _assert_observable_equal(before, _observable(m))
        # and the metric still folds correctly afterwards
        m.forward(jnp.asarray([7.0]))
        assert float(m.compute()) == 7.0

    def test_full_state_forward_failure_in_second_update(self):
        m = MaxMetric(nan_strategy="error", executor=False)
        m.update(jnp.asarray([5.0, 1.0]))
        before = _observable(m)
        (bad,) = faults.poison_batch(jnp.asarray([2.0, 3.0]), frac=0.5, seed=7)
        with pytest.raises(RuntimeError, match="nan"):
            m.forward(bad)
        _assert_observable_equal(before, _observable(m))

    @pytest.mark.parametrize("use_executor", [True, False], ids=["executor", "eager"])
    def test_reduce_forward_failure_restores_global_state(self, use_executor):
        m = BinaryAccuracy(validate_args=False, executor=use_executor)
        r = np.random.RandomState(0)
        m.update(jnp.asarray(r.rand(8).astype(np.float32)), jnp.asarray(r.randint(0, 2, 8)))
        before = _observable(m)
        with faults.raise_in_compute(m):
            with pytest.raises(faults.FaultInjected):
                m.forward(jnp.asarray(r.rand(4).astype(np.float32)), jnp.asarray(r.randint(0, 2, 4)))
        _assert_observable_equal(before, _observable(m))

    def test_collection_grouped_forward_failure_restores_leader(self):
        coll = MetricCollection(
            [MulticlassPrecision(num_classes=NUM_CLASSES, validate_args=False),
             MulticlassRecall(num_classes=NUM_CLASSES, validate_args=False)],
            executor=False,
        )
        coll.update(*_mc_batch(16, 0))  # resolves the shared stat-scores group
        assert any(len(g) > 1 for g in coll.compute_groups.values())
        leader = coll._modules[next(iter(coll.compute_groups.values()))[0]]
        before = _observable(leader)
        with faults.raise_in_compute(leader):
            with pytest.raises(faults.FaultInjected):
                coll.forward(*_mc_batch(8, 1))
        _assert_observable_equal(before, _observable(leader))


# ---------------------------------------------------------------------------
# executor dispatch failure after donation
# ---------------------------------------------------------------------------


class TestDispatchContainment:
    def _warm(self, m, batches=3):
        for i in range(batches):
            m.update(*_mc_batch(32, i))
        stats = executor_stats(m)
        assert stats["donated_calls"] >= 1, f"executor never donated: {stats}"
        return m

    def test_update_dispatch_failure_restores_donated_state(self):
        """A warm executable failing at dispatch — donated buffers consumed —
        restores the pre-call state from the host-side recovery reference,
        propagates the error, and does NOT disable the executor."""
        m = self._warm(MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=True))
        before = _observable(m)
        with faults.fail_dispatch(consume=True):
            with pytest.raises(faults.FaultInjected):
                m.update(*_mc_batch(32, 50))
        _assert_observable_equal(before, _observable(m))
        stats = executor_stats(m)
        assert stats["dispatch_failures"] == 1
        assert stats["recovery_restores"] == 1
        assert stats["disabled_reason"] is None, "a transient dispatch failure must not disable the executor"
        # the compiled path keeps working after the fault clears
        m.update(*_mc_batch(32, 51))
        assert executor_stats(m)["calls"] > stats["calls"]

    def test_forward_dispatch_failure_restores_donated_state(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=True)
        for i in range(3):
            m.forward(*_mc_batch(32, i))
        assert executor_stats(m)["donated_calls"] >= 1
        before = _observable(m)
        with faults.fail_dispatch(consume=True):
            with pytest.raises(faults.FaultInjected):
                m.forward(*_mc_batch(32, 60))
        _assert_observable_equal(before, _observable(m))
        assert executor_stats(m)["disabled_reason"] is None

    def test_collection_fused_dispatch_failure_restores_all_groups(self):
        coll = MetricCollection(
            [MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
             MulticlassPrecision(num_classes=NUM_CLASSES, validate_args=False)],
            executor=True,
        )
        for i in range(3):
            coll.update(*_mc_batch(32, i))
        assert executor_stats(coll)["donated_calls"] >= 1
        befores = {name: _observable(m) for name, m in coll._modules.items()}
        with faults.fail_dispatch(consume=True):
            with pytest.raises(faults.FaultInjected):
                coll.update(*_mc_batch(32, 70))
        for name, m in coll._modules.items():
            _assert_observable_equal(befores[name], _observable(m))
        assert executor_stats(coll)["disabled_reason"] is None
        coll.update(*_mc_batch(32, 71))  # fused path still alive

    def test_dispatch_failure_matches_eager_control_after_recovery(self):
        """End to end: fail one dispatch mid-stream, keep going — the final
        value must equal an eager control that never saw the fault."""
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=True)
        ctrl = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        for i in range(3):
            b = _mc_batch(32, i)
            m.update(*b)
            ctrl.update(*b)
        with faults.fail_dispatch(consume=True):
            with pytest.raises(faults.FaultInjected):
                m.update(*_mc_batch(32, 99))
        for i in range(3, 6):
            b = _mc_batch(32, i)
            m.update(*b)
            ctrl.update(*b)
        np.testing.assert_allclose(float(m.compute()), float(ctrl.compute()), rtol=1e-6)


# ---------------------------------------------------------------------------
# bounded multi-host sync
# ---------------------------------------------------------------------------


def _dist_metric(**kwargs):
    """A SumMetric that believes it runs multi-host, so compute() takes the
    process_allgather path (which the fault harness can hang/break)."""
    return SumMetric(nan_strategy="ignore", executor=False, distributed_available_fn=lambda: True, **kwargs)


class TestBoundedSync:
    def test_sync_timeout_raises_with_state_intact(self):
        m = _dist_metric(sync_timeout=0.2, on_sync_failure="raise")
        m.update(jnp.asarray([1.0, 2.0]))
        before = _observable(m)
        with faults.hang_sync(seconds=5.0):
            with pytest.raises(SyncTimeoutError):
                m.compute()
        _assert_observable_equal(before, _observable(m))
        assert m._is_synced is False and m._cache is None  # no half-synced residue
        assert float(m.compute()) == 3.0  # sane once the collective heals

    def test_sync_timeout_degrades_to_local(self):
        m = _dist_metric(sync_timeout=0.2, on_sync_failure="local")
        m.update(jnp.asarray([1.0, 2.0]))
        with faults.hang_sync(seconds=5.0):
            with pytest.warns(TorchMetricsUserWarning, match="local-only"):
                value = m.compute()
        assert float(value) == 3.0  # local data still served
        assert m.last_sync_ok is False
        # a later healthy sync clears the flag
        m._computed = None
        assert float(m.compute()) == 3.0
        assert m.last_sync_ok is True

    def test_broken_sync_degrades_to_local(self):
        m = _dist_metric(on_sync_failure="local")
        m.update(jnp.asarray([4.0]))
        with faults.break_sync():
            with pytest.warns(TorchMetricsUserWarning, match="local-only"):
                assert float(m.compute()) == 4.0
        assert m.last_sync_ok is False

    def test_broken_sync_raise_policy_propagates(self):
        m = _dist_metric(on_sync_failure="raise")
        m.update(jnp.asarray([4.0]))
        before = _observable(m)
        with faults.break_sync():
            with pytest.raises(faults.FaultInjected):
                m.compute()
        _assert_observable_equal(before, _observable(m))

    def test_sync_timeout_kwarg_validation(self):
        with pytest.raises(ValueError, match="sync_timeout"):
            SumMetric(nan_strategy="ignore", sync_timeout=-1)
        # "retry" joined the valid policies in ISSUE 4 (docs/DURABILITY.md)
        with pytest.raises(ValueError, match="on_sync_failure"):
            SumMetric(nan_strategy="ignore", on_sync_failure="give_up")
        with pytest.raises(ValueError, match="sync_retries"):
            SumMetric(nan_strategy="ignore", sync_retries=-2)


# ---------------------------------------------------------------------------
# validated restore
# ---------------------------------------------------------------------------


class TestValidatedRestore:
    def _src(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        m.update(*_mc_batch(16, 0))
        return m

    @pytest.mark.parametrize("mode", ["shape", "dtype", "structure"])
    def test_strict_rejects_corruption_target_untouched(self, mode):
        src = self._src()
        dst = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        dst.update(*_mc_batch(8, 1))
        before = _observable(dst)
        bad = faults.corrupt_state(src.state(), mode=mode)
        with pytest.raises(StateCorruptionError):
            dst.load_state(bad, validate="strict")
        _assert_observable_equal(before, _observable(dst))

    def test_check_finite_rejects_nan_state(self):
        src = MeanMetric(nan_strategy="ignore", executor=False)
        src.update(jnp.asarray([1.0, 2.0]))
        bad = faults.corrupt_state(src.state(), mode="nan")
        dst = MeanMetric(nan_strategy="ignore", executor=False)
        with pytest.raises(StateCorruptionError, match="non-finite"):
            dst.load_state(bad, check_finite=True)
        # without the finite check the same pytree installs (shapes/dtypes ok)
        dst.load_state(bad)

    def test_strict_is_default_and_structural(self):
        src = self._src()
        dst = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        with pytest.raises(StateCorruptionError):
            dst.load_state(faults.corrupt_state(src.state(), mode="structure"))
        # StateCorruptionError is still a KeyError for legacy callers
        with pytest.raises(KeyError):
            dst.load_state(faults.corrupt_state(src.state(), mode="structure"))

    def test_validate_off_installs_identically_zero_dispatch(self):
        """validate='off' must add zero device dispatches: the exported arrays
        are installed as-is (same objects), nothing new is created."""
        src = self._src()
        st = src.state()
        dst = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        dst.load_state(st, validate="off")
        for k in src._defaults:
            assert dst._state[k] is st[k]
        assert float(dst.compute()) == float(src.compute())

    def test_strict_happy_path_installs_identically(self):
        """strict validation is metadata-only: the round-trip still installs
        the exact same array objects (no casts, no dispatches)."""
        src = self._src()
        st = src.state()
        dst = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        dst.load_state(st, validate="strict")
        for k in src._defaults:
            assert dst._state[k] is st[k]

    def test_cast_mode_converts_dtype(self):
        src = self._src()
        st = src.state()
        field = next(iter(src._defaults))
        drifted = dict(st)
        drifted[field] = jnp.asarray(st[field]).astype(jnp.float32)
        dst = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        with pytest.raises(StateCorruptionError, match="dtype"):
            dst.load_state(drifted, validate="strict")
        dst.load_state(drifted, validate="cast")
        assert str(jnp.asarray(dst._state[field]).dtype) == str(jnp.asarray(st[field]).dtype)
        assert float(dst.compute()) == float(src.compute())

    def test_state_spec_shape_and_serialisable(self):
        import json

        m = self._src()
        spec = m.state_spec()
        assert spec["spec_version"] == 1 and spec["class"] == "MulticlassAccuracy"
        for fs in spec["fields"].values():
            assert fs["kind"] == "array" and fs["reduction"] == "sum" and fs["shape_invariant"]
        json.dumps(spec)  # persistable next to the checkpoint

    def test_collection_load_state_validates(self):
        coll = MetricCollection(
            [MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)], executor=False
        )
        coll.update(*_mc_batch(16, 0))
        states = coll.state()
        leader = next(iter(states))
        bad = dict(states)
        bad[leader] = faults.corrupt_state(states[leader], mode="dtype")
        coll2 = MetricCollection(
            [MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)], executor=False
        )
        with pytest.raises(StateCorruptionError):
            coll2.load_state(bad)
        coll2.load_state(bad, validate="cast")
        assert coll2.state_spec().keys() == states.keys()


# ---------------------------------------------------------------------------
# resume mid-epoch under the executor (satellite)
# ---------------------------------------------------------------------------


class TestResumeUnderExecutor:
    @pytest.mark.parametrize(
        "src_executor,dst_executor",
        [(True, True), (False, True), (True, False)],
        ids=["executor-to-executor", "eager-to-executor", "executor-to-eager"],
    )
    def test_forward_resume_matches_uninterrupted(self, src_executor, dst_executor):
        """state() -> load_state() -> continued forward under the executor is
        indistinguishable from never suspending — including states produced by
        the other path (satellite: only the eager path was covered)."""
        straight = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=dst_executor)
        suspended = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=src_executor)
        batches = [_mc_batch(32, i) for i in range(6)]
        for b in batches[:3]:
            np.testing.assert_allclose(
                np.asarray(straight.forward(*b)), np.asarray(suspended.forward(*b)), rtol=1e-5
            )
        resumed = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=dst_executor)
        resumed.load_state(suspended.state())
        assert resumed.update_count == suspended.update_count
        for b in batches[3:]:
            np.testing.assert_allclose(
                np.asarray(straight.forward(*b)), np.asarray(resumed.forward(*b)), rtol=1e-5
            )
        np.testing.assert_allclose(
            np.asarray(straight.compute()), np.asarray(resumed.compute()), rtol=1e-6
        )

    def test_update_resume_under_executor_with_donation(self):
        """The restored state must survive the executor's donation machinery:
        after load_state the first compiled call copies (the arrays are
        externally aliased), then donation streaks resume."""
        straight = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=True)
        part = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=True)
        batches = [_mc_batch(32, 10 + i) for i in range(6)]
        for b in batches[:3]:
            straight.update(*b)
            part.update(*b)
        st = part.state()
        resumed = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=True)
        resumed.load_state(st)
        for b in batches[3:]:
            straight.update(*b)
            resumed.update(*b)
        np.testing.assert_allclose(float(straight.compute()), float(resumed.compute()), rtol=1e-6)
        # the checkpointed pytree is still intact (not consumed by donation)
        for k, v in st.items():
            np.asarray(v)  # a donated-away buffer would raise on access


# ---------------------------------------------------------------------------
# functional_sync reserved count key (satellite regression)
# ---------------------------------------------------------------------------


def _smap():
    return partial(shard_map_compat, check_vma=False)  # version-portable


class TestFunctionalSyncCountKey:
    def test_state_export_syncs_with_summed_count(self, mesh):
        """functional_sync on a state() export (which carries the reserved
        '_update_count' int leaf) must strip the count from the collectives
        and re-attach it summed across ranks — it used to be all-gathered
        into a stacked per-rank array (or crash under jit)."""
        from jax.sharding import PartitionSpec as P

        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        for i in range(3):
            m.update(*_mc_batch(16, i))
        st = jax.tree_util.tree_map(jnp.asarray, m.state())
        assert "_update_count" in st

        fn = _smap()(
            lambda s: m.functional_sync(s, "batch"),
            mesh=mesh,
            in_specs=(P(),),
            out_specs=P(),
        )
        synced = jax.jit(fn)(st)
        world = mesh.devices.size
        assert int(synced["_update_count"]) == 3 * world
        assert np.asarray(synced["_update_count"]).ndim == 0  # scalar, not stacked
        for k in m._defaults:
            np.testing.assert_allclose(
                np.asarray(synced[k]), world * np.asarray(st[k]), rtol=1e-6
            )

    def test_collection_functional_sync_strips_count(self, mesh):
        from jax.sharding import PartitionSpec as P

        coll = MetricCollection(
            [MulticlassPrecision(num_classes=NUM_CLASSES, validate_args=False),
             MulticlassRecall(num_classes=NUM_CLASSES, validate_args=False)],
            executor=False,
        )
        for i in range(2):
            coll.update(*_mc_batch(16, i))
        states = jax.tree_util.tree_map(jnp.asarray, coll.state())
        leader = next(iter(states))
        assert "_update_count" in states[leader]

        fn = _smap()(
            lambda s: coll.functional_sync(s, "batch"),
            mesh=mesh,
            in_specs=(P(),),
            out_specs=P(),
        )
        synced = jax.jit(fn)(states)
        world = mesh.devices.size
        assert int(synced[leader]["_update_count"]) == 2 * world
        for k, v in states[leader].items():
            if k == "_update_count":
                continue
            np.testing.assert_allclose(np.asarray(synced[leader][k]), world * np.asarray(v), rtol=1e-6)

    def test_eager_roundtrip_after_synced_state_load(self, mesh):
        """The synced export (summed count included) loads back into a fresh
        metric with the count reflecting the world-wide update total."""
        from jax.sharding import PartitionSpec as P

        m = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        m.update(*_mc_batch(16, 0))
        st = jax.tree_util.tree_map(jnp.asarray, m.state())
        fn = _smap()(lambda s: m.functional_sync(s, "batch"), mesh=mesh, in_specs=(P(),), out_specs=P())
        synced = jax.jit(fn)(st)
        m2 = MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False, executor=False)
        m2.load_state(synced)
        assert m2.update_count == mesh.devices.size


# ---------------------------------------------------------------------------
# executor fallback diagnosis (satellite)
# ---------------------------------------------------------------------------


class TestExecutorStatus:
    def test_static_ineligibility_is_surfaced(self):
        from torchmetrics_tpu import CatMetric

        m = CatMetric(nan_strategy="ignore")  # list state -> statically ineligible
        m.update(jnp.asarray([1.0, 2.0]))
        status = m.executor_status
        assert status["enabled"] is True and status["engaged"] is False
        assert "list states" in status["fallback_reason"]

    def test_disabled_instance_reports_clean(self):
        m = SumMetric(nan_strategy="ignore", executor=False)
        m.update(jnp.asarray([1.0]))
        status = m.executor_status
        assert status["enabled"] is False
        assert status["fallback_reason"] is None

    def test_sticky_trace_fallback_logs_once_at_debug(self, caplog):
        class Untraceable(Metric):
            full_state_update = False

            def __init__(self, **kwargs):
                super().__init__(**kwargs)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, x):
                if float(x.sum()) > -1e30:  # host branch on traced value
                    self.total = self.total + x.sum()

            def compute(self):
                return self.total

        m = Untraceable(executor=True)
        with caplog.at_level(logging.DEBUG, logger="torchmetrics_tpu"):
            m.update(jnp.asarray([1.0]))
            m.update(jnp.asarray([2.0]))
        assert float(m.compute()) == 3.0
        msgs = [r.message for r in caplog.records if "executor disabled" in r.message]
        assert len(msgs) == 1, msgs  # once, not per call
        assert "Untraceable" in msgs[0]
        assert m.executor_status["fallback_reason"] is not None

    def test_collection_status_includes_members(self):
        coll = MetricCollection([SumMetric(nan_strategy="ignore")], executor=False)
        status = coll.executor_status
        assert status["enabled"] is False
        assert "SumMetric" in status["members"]
