"""Real-data fixture tests: our metrics vs reference-oracle goldens.

The committed asset pack (tests/fixtures_real/: natural photos from sklearn's
bundled sample images, deterministic formant-synthesized speech clips, a
multilingual EN/ZH/JA text corpus) plays the role of the reference's S3 data
pack (reference Makefile:43-46). Goldens were computed offline by running the
reference implementation itself on CPU torch
(tools/gen_real_fixture_goldens.py) — so these tests compare our JAX
implementations against the actual reference behavior on natural-image
statistics, CJK tokenization corner cases, and speech-shaped signals rather
than synthetic arrays.
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # real-data asset pack oracles; run with --runslow

sys.path.insert(0, "/root/repo/tests")

from functools import lru_cache  # noqa: E402

from helpers.real_fixtures import (  # noqa: E402
    degraded_image,
    degraded_speech,
    load_goldens,
    load_images,
    load_speech,
    load_text,
)

# lazy: a missing/corrupt goldens.json should fail the tests that need it,
# not abort collection of the whole module
gold = lru_cache(maxsize=1)(load_goldens)


class TestRealImages:
    """SSIM/PSNR/UQI/VIF/... on natural photos vs reference values."""

    # (golden key, our functional name, kwargs, rtol)
    # UQI gets a wider tolerance: it has no SSIM-style C1/C2 stabilisers, so
    # flat windows (blurred sky/background) give ~0/(0+eps) ratios where any
    # float32 conv-ordering difference vs torch is amplified; the deviation is
    # the metric's documented ill-conditioning, not an implementation gap
    CASES = [
        ("ssim", "structural_similarity_index_measure", {"data_range": 1.0}, 1e-3),
        ("psnr", "peak_signal_noise_ratio", {"data_range": 1.0}, 1e-3),
        ("uqi", "universal_image_quality_index", {}, 1e-2),
        ("vif", "visual_information_fidelity", {}, 5e-3),
        ("sam", "spectral_angle_mapper", {}, 1e-3),
        ("ergas", "error_relative_global_dimensionless_synthesis", {}, 1e-3),
        ("scc", "spatial_correlation_coefficient", {}, 1e-3),
        ("rmse_sw", "root_mean_squared_error_using_sliding_window", {}, 1e-3),
        ("ms_ssim", "multiscale_structural_similarity_index_measure", {"data_range": 1.0}, 1e-3),
    ]

    @pytest.mark.parametrize("image_name", ["china", "flower"])
    @pytest.mark.parametrize("kind", ["noise", "blur", "contrast"])
    def test_image_metrics(self, image_name, kind):
        import torchmetrics_tpu.functional.image as FI

        img = load_images()[image_name]
        clean = jnp.asarray((img.astype(np.float64) / 255.0).transpose(2, 0, 1)[None], dtype=jnp.float32)
        deg = jnp.asarray(degraded_image(img, kind).transpose(2, 0, 1)[None], dtype=jnp.float32)
        golden = gold()["image"][f"{image_name}_{kind}"]
        for key, fn_name, kwargs, rtol in self.CASES:
            if key not in golden:
                continue
            ours = float(getattr(FI, fn_name)(deg, clean, **kwargs))
            np.testing.assert_allclose(
                ours, golden[key], rtol=rtol, atol=1e-4, err_msg=f"{fn_name} on {image_name}_{kind}"
            )

    @pytest.mark.parametrize("image_name", ["china", "flower"])
    def test_total_variation(self, image_name):
        import torchmetrics_tpu.functional.image as FI

        img = load_images()[image_name]
        clean = jnp.asarray((img.astype(np.float64) / 255.0).transpose(2, 0, 1)[None], dtype=jnp.float32)
        ours = float(FI.total_variation(clean))
        np.testing.assert_allclose(ours, gold()["image"][f"{image_name}_tv"], rtol=1e-3)


class TestRealText:
    def test_english_suite(self):
        import torchmetrics_tpu.functional.text as FT

        corpus = load_text()["english"]
        golden = gold()["text"]["english"]
        preds, targets = corpus["preds"], corpus["targets"]
        listed = [[t] for t in targets]
        results = {
            "bleu": float(FT.bleu_score(preds, listed)),
            "sacre_bleu_13a": float(FT.sacre_bleu_score(preds, listed, tokenize="13a")),
            "sacre_bleu_intl": float(FT.sacre_bleu_score(preds, listed, tokenize="intl")),
            "chrf": float(FT.chrf_score(preds, listed)),
            "ter": float(FT.translation_edit_rate(preds, listed)),
            "wer": float(FT.word_error_rate(preds, targets)),
            "cer": float(FT.char_error_rate(preds, targets)),
            "mer": float(FT.match_error_rate(preds, targets)),
            "wil": float(FT.word_information_lost(preds, targets)),
        }
        for key, ours in results.items():
            np.testing.assert_allclose(ours, golden[key], rtol=1e-4, err_msg=f"english {key}")

    def test_english_edit_distance(self):
        """Ours is exact Levenshtein; the reference's banded TER helper
        (reference functional/text/helper.py:54-295) overestimates by 1 on one
        heavily-reordered pair (54.75 vs the true 54.5 mean) — assert exactness
        against an independent DP and stay within that band of the golden."""
        import torchmetrics_tpu.functional.text as FT

        corpus = load_text()["english"]

        def lev(a, b):
            prev = list(range(len(b) + 1))
            for i, ca in enumerate(a, 1):
                cur = [i] + [0] * len(b)
                for j, cb in enumerate(b, 1):
                    cur[j] = min(prev[j - 1] + (ca != cb), prev[j] + 1, cur[j - 1] + 1)
                prev = cur
            return prev[-1]

        exact = np.mean([lev(p, t) for p, t in zip(corpus["preds"], corpus["targets"])])
        ours = float(FT.edit_distance(corpus["preds"], corpus["targets"]))
        np.testing.assert_allclose(ours, exact, rtol=0, atol=0)
        assert abs(ours - gold()["text"]["english"]["edit"]) <= 1.0 / len(corpus["preds"]) + 1e-9

    def test_english_rouge(self):
        import torchmetrics_tpu.functional.text as FT

        corpus = load_text()["english"]
        rouge = FT.rouge_score(corpus["preds"], corpus["targets"], rouge_keys=("rouge1", "rouge2", "rougeL"))
        for key, val in gold()["text"]["english"]["rouge"].items():
            np.testing.assert_allclose(float(rouge[key]), val, rtol=1e-4, err_msg=f"rouge {key}")

    @pytest.mark.parametrize("lang", ["chinese", "japanese"])
    def test_cjk_suite(self, lang):
        """CJK tokenization corner cases: char-level SacreBLEU, chrF, CER."""
        import torchmetrics_tpu.functional.text as FT

        corpus = load_text()[lang]
        golden = gold()["text"][lang]
        preds, targets = corpus["preds"], corpus["targets"]
        listed = [[t] for t in targets]
        np.testing.assert_allclose(
            float(FT.sacre_bleu_score(preds, listed, tokenize="char")),
            golden["sacre_bleu_char"], rtol=1e-4, err_msg=f"{lang} sacre_bleu char",
        )
        np.testing.assert_allclose(
            float(FT.chrf_score(preds, listed)), golden["chrf"], rtol=1e-4, err_msg=f"{lang} chrf"
        )
        np.testing.assert_allclose(
            float(FT.char_error_rate(preds, targets)), golden["cer"], rtol=1e-4, err_msg=f"{lang} cer"
        )

    def test_chinese_zh_tokenizer(self):
        import torchmetrics_tpu.functional.text as FT

        corpus = load_text()["chinese"]
        np.testing.assert_allclose(
            float(FT.sacre_bleu_score(corpus["preds"], [[t] for t in corpus["targets"]], tokenize="zh")),
            gold()["text"]["chinese"]["sacre_bleu_zh"], rtol=1e-4,
        )


class TestRealAudio:
    @pytest.mark.parametrize("clip", ["clip1", "clip2"])
    @pytest.mark.parametrize("snr_db", [20, 5])
    def test_snr_family(self, clip, snr_db):
        import torchmetrics_tpu.functional.audio as FA

        speech = load_speech()
        clean = jnp.asarray(speech[clip])
        deg = jnp.asarray(degraded_speech(speech[clip], snr_db))
        golden = gold()["audio"][f"{clip}_snr{snr_db}"]
        np.testing.assert_allclose(float(FA.signal_noise_ratio(deg, clean)), golden["snr"], rtol=1e-3)
        np.testing.assert_allclose(
            float(FA.scale_invariant_signal_noise_ratio(deg, clean)), golden["si_snr"], rtol=1e-3
        )
        np.testing.assert_allclose(
            float(FA.scale_invariant_signal_distortion_ratio(deg, clean)), golden["si_sdr"], rtol=1e-3
        )
        # sdr keeps the batch axis ((1,) for (1, T) input, like the reference)
        np.testing.assert_allclose(
            float(FA.signal_distortion_ratio(deg[None], clean[None])[0]), golden["sdr"], rtol=5e-3
        )

    @pytest.mark.parametrize("clip", ["clip1", "clip2"])
    def test_stoi_monotone_and_srmr_runs(self, clip):
        """The wheel-backed reference can't run STOI/SRMR here; on real-shaped
        speech, pin the behavioral invariant instead: STOI degrades with SNR
        and SRMR produces a finite score (their numeric parity is covered by
        the oracle tests in tests/audio/test_dsp.py)."""
        import torchmetrics_tpu.functional.audio as FA

        speech = load_speech()
        fs = int(speech["fs"])
        clean = jnp.asarray(speech[clip])
        stoi_vals = [
            float(FA.short_time_objective_intelligibility(jnp.asarray(degraded_speech(speech[clip], s)), clean, fs))
            for s in (20, 5)
        ]
        assert stoi_vals[0] > stoi_vals[1], f"STOI not monotone in SNR: {stoi_vals}"
        # (1,) return for 1-D input is deliberate reference-quirk parity
        srmr = float(FA.speech_reverberation_modulation_energy_ratio(clean, fs)[0])
        assert np.isfinite(srmr)
