"""Pins for the bench harness's result-cache and retry machinery.

bench.py is driver-facing infrastructure: the round's TPU evidence chain rests
on its (config, backend, workload-hash) cache, symmetric stall retries, and
honest provenance labeling. These tests exercise that machinery with stub
workloads — no timing, no accelerator, no subprocess probe.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


@pytest.fixture()
def cache_path(tmp_path, monkeypatch):
    path = str(tmp_path / "bench_cache.json")
    monkeypatch.setattr(bench, "CACHE_PATH", path)
    return path


@pytest.fixture(autouse=True)
def _no_retry_cooldown(monkeypatch):
    """The 10 s stall-retry cool-down is real-world backoff, not test subject."""
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


def test_code_hash_stable_and_config_sensitive():
    h1 = bench._code_hash("1_accuracy_update", bench.bench_config1)
    assert h1 == bench._code_hash("1_accuracy_update", bench.bench_config1)
    assert h1 != bench._code_hash("6_binned_curve_pallas", bench.bench_config6)
    assert len(h1) == 16


def test_store_load_roundtrip_atomic(cache_path):
    cache = {}
    bench._store_cache(cache, "cfg", "tpu", "abcd", {"value": 1.5, "vs_baseline": 2.0})
    assert not os.path.exists(cache_path + ".tmp")  # atomic replace, no leftovers
    loaded = bench._load_cache()
    entry = loaded["cfg"]["tpu"]
    assert entry["code_hash"] == "abcd"
    assert entry["result"]["value"] == 1.5
    assert entry["captured_at"]  # provenance recorded


def test_load_cache_tolerates_corruption(cache_path):
    with open(cache_path, "w") as f:
        f.write("{ truncated")
    assert bench._load_cache() == {}


def test_run_config_retries_only_on_stall_signal():
    calls = []

    def stable():
        calls.append(1)
        return {"value": 1.0, "vs_baseline": 0.5}  # losing ratio alone must NOT retry

    r = bench._run_config(stable)
    assert len(calls) == 1 and r["value"] == 1.0 and "retried_after_stall" not in r

    calls.clear()

    def stall_then_clean():
        calls.append(1)
        if len(calls) == 1:
            bench._TIMING_UNSTABLE.append(True)
            return {"value": 99.0}
        return {"value": 2.0}

    r = bench._run_config(stall_then_clean)
    # the retry REPLACES the measurement (same statistic, not best-of-two)
    assert len(calls) == 2 and r["value"] == 2.0 and r["retried_after_stall"] is True


def test_run_config_keeps_first_result_when_retry_errors():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            bench._TIMING_UNSTABLE.append(True)
            return {"value": 42.0}
        raise RuntimeError("tunnel died")

    r = bench._run_config(flaky)
    assert r["value"] == 42.0 and r["timing_unstable"] and "retry_errored" in r


def test_run_config_propagates_subprocess_stall_flag():
    calls = []

    def sub():
        calls.append(1)
        return {"value": 3.0, "timing_unstable": True} if len(calls) == 1 else {"value": 4.0}

    r = bench._run_config(sub)
    assert len(calls) == 2 and r["value"] == 4.0


def test_stable_min_flags_nonconvergence():
    del bench._TIMING_UNSTABLE[:]
    seq = iter([1.0, 5.0, 5.0, 5.0, 5.0, 5.0, 5.0, 5.0])
    assert bench._stable_min(lambda: next(seq), repeats=2, max_extra=3) == 1.0
    assert bench._TIMING_UNSTABLE
    del bench._TIMING_UNSTABLE[:]
    seq = iter([2.0, 2.1])
    assert bench._stable_min(lambda: next(seq), repeats=2) == 2.0
    assert not bench._TIMING_UNSTABLE


def test_cache_reuse_and_provenance(cache_path, monkeypatch):
    """Degraded-backend main(): cached TPU rows are reused with provenance;
    configs without a matching capture run live and mark the run degraded."""
    fake_result = {"value": 123.0, "vs_baseline": 9.9, "unit": "fake tpu row"}
    cache = {}
    for name, fn in bench.DEVICE_CONFIGS:
        bench._store_cache(cache, name, "tpu", bench._code_hash(name, fn), fake_result)
    # one config's hash no longer matches (simulated code change)
    stale = json.load(open(cache_path))
    stale["3_ssim_psnr"]["tpu"]["code_hash"] = "stale"
    with open(cache_path, "w") as f:
        json.dump(stale, f)

    monkeypatch.setattr(bench, "_ensure_backend", lambda: "cpu (accelerator unavailable)")
    live_runs = []

    def fake_run(fn):
        live_runs.append(getattr(fn, "__name__", "sub"))
        return {"value": 1.0, "vs_baseline": 1.2}

    monkeypatch.setattr(bench, "_run_config", fake_run)
    monkeypatch.setattr(bench, "_run_in_cpu_subprocess", lambda name: {"value": 1.0})

    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [ln for ln in buf.getvalue().strip().splitlines() if ln.strip()]
    assert len(lines) == 1, "driver contract: exactly ONE JSON line"
    out = json.loads(lines[0])
    assert out["backend_degraded"] is True  # the stale config fell back to CPU
    assert out["tpu_provenance"]["cpu_only"] == ["3_ssim_psnr"]
    assert sorted(out["tpu_provenance"]["cache"]) == sorted(
        n for n, _ in bench.DEVICE_CONFIGS if n != "3_ssim_psnr"
    )
    cached_row = out["configs"]["1_accuracy_update"]
    assert cached_row["source"] == "tpu_result_cache" and cached_row["value"] == 123.0
    assert cached_row["captured_at"]


def test_all_cached_reports_tpu_backend(cache_path, monkeypatch):
    fake_result = {"value": 5.0, "vs_baseline": 2.0}
    cache = {}
    for name, fn in bench.DEVICE_CONFIGS:
        bench._store_cache(cache, name, "tpu", bench._code_hash(name, fn), fake_result)
    monkeypatch.setattr(bench, "_ensure_backend", lambda: "cpu (accelerator unavailable)")
    monkeypatch.setattr(bench, "_run_in_cpu_subprocess", lambda name: {"value": 1.0})

    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["backend_degraded"] is False
    assert out["backend"] == "tpu (from result cache)"


def test_apply_baselines_fills_null_ratios_only(tmp_path, monkeypatch):
    """vs_baseline null -> filled from BASELINE.json bench_baselines with the
    source labelled; a live torch ratio is never overwritten (ISSUE 3
    satellite: the perf trajectory is tracked run-over-run)."""
    baselines = {
        "2_collection_mesh_sync": {"value": 2000.0, "value_same_work_unsynced": 6000.0},
    }
    r = bench._apply_baselines(
        "2_collection_mesh_sync",
        {"value": 2100.0, "vs_baseline": None, "value_same_work_unsynced": 3000.0, "vs_baseline_same_work": None},
        baselines,
    )
    assert r["vs_baseline"] == 1.05
    assert r["vs_baseline_same_work"] == 0.5
    assert r["baseline_source"] == "BASELINE.json bench_baselines"
    # live ratio wins: nothing touched, no source label
    r2 = bench._apply_baselines("2_collection_mesh_sync", {"value": 2100.0, "vs_baseline": 3.3}, baselines)
    assert r2["vs_baseline"] == 3.3 and "baseline_source" not in r2
    # unknown config / missing baseline: untouched
    r3 = bench._apply_baselines("nope", {"value": 1.0, "vs_baseline": None}, baselines)
    assert r3["vs_baseline"] is None


def test_committed_baselines_cover_every_config():
    """BASELINE.json's bench_baselines block stays in lockstep with the
    configs bench.py actually runs."""
    baselines = bench._load_baselines()
    names = [n for n, _ in bench.DEVICE_CONFIGS] + ["2_collection_mesh_sync"]
    for name in names:
        assert baselines.get(name, {}).get("value"), f"no committed baseline for {name}"
