"""Generic lifecycle properties swept across every buildable metric class.

The reference's `MetricTester._class_test` runs the same lifecycle battery
(pickle, clone, reset, repeated update) on every metric; this sweep reuses the
doctest-generator registry (tools/gen_doctests.py) to instantiate ~170 metric
classes with valid inputs and assert the core `Metric` contract on each:

1. two updates + compute succeed;
2. pickle round-trip preserves the computed value;
3. ``clone()`` is state-independent of the original;
4. ``reset()`` + one update reproduces the single-update value.
"""
import pathlib
import pickle
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
import gen_doctests as reg  # noqa: E402

DOMAINS = [
    "classification", "regression", "clustering", "nominal", "retrieval",
    "aggregation", "audio", "image", "text",
]

# classes whose example the registry cannot build generically (hook-based or
# covered by dedicated tests elsewhere)
SWEEP_SKIP = reg.SKIP | {
    "BERTScore", "InfoLM",  # model-hook classes: dedicated tests in tests/text
    "FrechetInceptionDistance", "InceptionScore", "KernelInceptionDistance",
    "MemorizationInformedFrechetInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity", "PerceptualPathLength",
}


def _collect_cases():
    cases = []
    for domain in DOMAINS:
        pkg_dir = reg.ROOT / reg.PKG / domain
        files = sorted(pkg_dir.glob("*.py")) if pkg_dir.is_dir() else [reg.ROOT / reg.PKG / f"{domain}.py"]
        for f in files:
            if f.name == "__init__.py":
                continue
            module_name = f"{reg.PKG}.{domain}.{f.stem}" if pkg_dir.is_dir() else f"{reg.PKG}.{domain}"
            for cls_name in reg.classes_in_module(module_name):
                if cls_name in SWEEP_SKIP:
                    continue
                flavour = reg.FLAVOUR_OVERRIDE.get(cls_name) or reg._flavour(cls_name)
                if domain in reg.DOMAIN_DEFAULTS and flavour is None:
                    setup, default_ctor, default_upd = reg.DOMAIN_DEFAULTS[domain]
                elif flavour == "binary":
                    setup, default_ctor, default_upd = reg.BINARY_SETUP, "", "preds, target"
                elif flavour == "multiclass":
                    setup, default_ctor, default_upd = reg.MULTICLASS_SETUP, "num_classes=3", "preds, target"
                elif flavour == "multilabel":
                    setup, default_ctor, default_upd = reg.MULTILABEL_SETUP, "num_labels=3", "preds, target"
                elif domain == "text":
                    setup, default_ctor, default_upd = (
                        ["import jax.numpy as jnp"] + reg.TEXT_GEN_SETUP, "", "preds, target")
                else:
                    setup, default_ctor, default_upd = (
                        reg.MULTICLASS_SETUP, 'task="multiclass", num_classes=3', "preds, target")
                ctor = reg.CTOR.get(cls_name, default_ctor)
                setup = reg.SETUP_OVERRIDE_LINES.get(cls_name, setup) + reg.EXTRA_SETUP.get(cls_name, [])
                upd = reg.UPDATE_ARGS.get(cls_name, default_upd)
                cases.append(pytest.param(module_name, cls_name, ctor, tuple(setup), upd, id=cls_name))
    return cases


# text classes use the generic pair; patch the ASR ones to flat string targets
_TEXT_FLAT = {"WordErrorRate", "CharErrorRate", "MatchErrorRate", "WordInfoLost",
              "WordInfoPreserved", "EditDistance"}

CASES = _collect_cases()


def _build(module_name, cls_name, ctor, setup, upd):
    ns = {}
    lines = [f"from {module_name} import {cls_name}"] + list(setup)
    if cls_name in _TEXT_FLAT:
        lines += ['preds = ["this is the answer"]', 'target = ["this was the answer"]']
    elif cls_name == "Perplexity":
        lines += ["preds = jnp.full((1, 4, 6), 1 / 6)", "target = jnp.asarray([[0, 1, 2, 3]])"]
    elif cls_name == "SQuAD":
        lines += reg.FN_SETUP["squad"]
    lines.append(f"m = {cls_name}({ctor})")
    for ln in lines:
        exec(ln, ns)
    return ns, upd


def _tree_allclose(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", CASES)
def test_lifecycle(module_name, cls_name, ctor, setup, upd):
    ns, upd = _build(module_name, cls_name, ctor, setup, upd)
    m = ns["m"]

    # 1. repeated update + compute
    exec(f"m.update({upd})", ns)
    v1 = m.compute()
    exec(f"m.update({upd})", ns)
    v2 = m.compute()

    # 2. pickle round-trip preserves the computed value
    m2 = pickle.loads(pickle.dumps(m))
    _tree_allclose(m2.compute(), v2)

    # 3. clone is independent: updating the clone leaves the original unchanged
    c = m.clone()
    ns_c = dict(ns); ns_c["m"] = c
    exec(f"m.update({upd})", ns_c)
    _tree_allclose(m.compute(), v2)

    # 4. reset + single update reproduces the first value
    m.reset()
    exec(f"m.update({upd})", ns)
    _tree_allclose(m.compute(), v1)
