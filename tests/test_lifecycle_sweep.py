"""Generic lifecycle properties swept across every buildable metric class.

The reference's `MetricTester._class_test` runs the same lifecycle battery
(pickle, clone, reset, repeated update) on every metric; this sweep reuses the
doctest-generator registry (tools/gen_doctests.py) to instantiate ~170 metric
classes with valid inputs and assert the core `Metric` contract on each:

1. two updates + compute succeed;
2. pickle round-trip preserves the computed value;
3. ``clone()`` is state-independent of the original;
4. ``reset()`` + one update reproduces the single-update value.
"""
import pathlib
import pickle
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # registry sweep over ~170 classes; run with --runslow

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
import gen_doctests as reg  # noqa: E402

DOMAINS = [
    "classification", "regression", "clustering", "nominal", "retrieval",
    "aggregation", "audio", "image", "text",
]

# classes whose example the registry cannot build generically (hook-based or
# covered by dedicated tests elsewhere)
SWEEP_SKIP = reg.SKIP | {
    "BERTScore", "InfoLM",  # model-hook classes: dedicated tests in tests/text
    "FrechetInceptionDistance", "InceptionScore", "KernelInceptionDistance",
    "MemorizationInformedFrechetInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity", "PerceptualPathLength",
}


def _collect_cases():
    cases = []
    for domain in DOMAINS:
        pkg_dir = reg.ROOT / reg.PKG / domain
        files = sorted(pkg_dir.glob("*.py")) if pkg_dir.is_dir() else [reg.ROOT / reg.PKG / f"{domain}.py"]
        for f in files:
            if f.name == "__init__.py":
                continue
            module_name = f"{reg.PKG}.{domain}.{f.stem}" if pkg_dir.is_dir() else f"{reg.PKG}.{domain}"
            for cls_name in reg.classes_in_module(module_name):
                if cls_name in SWEEP_SKIP:
                    continue
                flavour = reg.FLAVOUR_OVERRIDE.get(cls_name) or reg._flavour(cls_name)
                if domain in reg.DOMAIN_DEFAULTS and flavour is None:
                    setup, default_ctor, default_upd = reg.DOMAIN_DEFAULTS[domain]
                elif flavour == "binary":
                    setup, default_ctor, default_upd = reg.BINARY_SETUP, "", "preds, target"
                elif flavour == "multiclass":
                    setup, default_ctor, default_upd = reg.MULTICLASS_SETUP, "num_classes=3", "preds, target"
                elif flavour == "multilabel":
                    setup, default_ctor, default_upd = reg.MULTILABEL_SETUP, "num_labels=3", "preds, target"
                elif domain == "text":
                    setup, default_ctor, default_upd = (
                        ["import jax.numpy as jnp"] + reg.TEXT_GEN_SETUP, "", "preds, target")
                else:
                    setup, default_ctor, default_upd = (
                        reg.MULTICLASS_SETUP, 'task="multiclass", num_classes=3', "preds, target")
                ctor = reg.CTOR.get(cls_name, default_ctor)
                setup = reg.SETUP_OVERRIDE_LINES.get(cls_name, setup) + reg.EXTRA_SETUP.get(cls_name, [])
                upd = reg.UPDATE_ARGS.get(cls_name, default_upd)
                cases.append(pytest.param(module_name, cls_name, ctor, tuple(setup), upd, id=cls_name))
    return cases


# text classes use the generic pair; patch the ASR ones to flat string targets
_TEXT_FLAT = {"WordErrorRate", "CharErrorRate", "MatchErrorRate", "WordInfoLost",
              "WordInfoPreserved", "EditDistance"}


# ---------------------------------------------------------------------------
# hand-specified cases for domains outside the registry: detection, multimodal,
# model-backed image (picklable module-level hooks), and wrappers
# ---------------------------------------------------------------------------

def _feat(x):
    """Picklable toy feature extractor for the inception-family metrics."""
    return x.mean(axis=(2, 3))


def _img_embed(images, texts):
    """Picklable toy joint embedder for CLIPScore."""
    img_f = jnp.stack([img.mean(axis=(1, 2)) for img in images])
    txt_f = jnp.asarray([[len(t), t.count("a"), 1.0] for t in texts], dtype=jnp.float32)
    return img_f, txt_f


def _txt_embed(texts):
    return jnp.asarray([[len(t), t.count("o"), 1.0] for t in texts], dtype=jnp.float32)


def _lpips_net(a, b):
    return jnp.mean((a - b) ** 2, axis=(1, 2, 3))


_DET_SETUP = (
    "import jax.numpy as jnp",
    'preds = [{"boxes": jnp.asarray([[10.0, 10.0, 20.0, 20.0]]),'
    ' "scores": jnp.asarray([0.8]), "labels": jnp.asarray([0])}]',
    'target = [{"boxes": jnp.asarray([[12.0, 10.0, 22.0, 20.0]]), "labels": jnp.asarray([0])}]',
)
_PANOPTIC_SETUP = (
    "import jax.numpy as jnp",
    "preds = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])",
    "target = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [0, 0], [1, 0]]])",
)
_IMG8 = (
    "import jax.numpy as jnp",
    "real = (jnp.arange(4 * 3 * 8 * 8).reshape(4, 3, 8, 8) % 255) / 255.0",
    "fake = 1.0 - real",
)
_CLS_SETUP = (
    "import jax.numpy as jnp",
    "from torchmetrics_tpu.classification import BinaryAccuracy",
    "preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])",
    "target = jnp.asarray([0, 1, 1, 0])",
)

EXTRA_CASES = [
    ("torchmetrics_tpu.detection", "IntersectionOverUnion", "", _DET_SETUP, "preds, target"),
    ("torchmetrics_tpu.detection", "GeneralizedIntersectionOverUnion", "", _DET_SETUP, "preds, target"),
    ("torchmetrics_tpu.detection", "DistanceIntersectionOverUnion", "", _DET_SETUP, "preds, target"),
    ("torchmetrics_tpu.detection", "CompleteIntersectionOverUnion", "", _DET_SETUP, "preds, target"),
    ("torchmetrics_tpu.detection", "MeanAveragePrecision", "", _DET_SETUP, "preds, target"),
    ("torchmetrics_tpu.detection", "PanopticQuality", "things={0}, stuffs={1}", _PANOPTIC_SETUP, "preds, target"),
    ("torchmetrics_tpu.detection", "ModifiedPanopticQuality", "things={0}, stuffs={1}", _PANOPTIC_SETUP,
     "preds, target"),
    ("torchmetrics_tpu.multimodal", "CLIPScore", "embedding_fn=_img_embed",
     _IMG8 + ("from test_lifecycle_sweep import _img_embed",
              'texts = ["a photo of a cat", "a photo of a dog", "a bird", "a fish"]'), "real, texts"),
    ("torchmetrics_tpu.multimodal", "CLIPImageQualityAssessment",
     "image_embedding_fn=_feat, text_embedding_fn=_txt_embed",
     _IMG8 + ("from test_lifecycle_sweep import _feat, _txt_embed",), "real"),
    ("torchmetrics_tpu.image", "FrechetInceptionDistance", "feature_extractor=_feat, num_features=3",
     _IMG8 + ("from test_lifecycle_sweep import _feat",), ("real, real=True", "fake, real=False")),
    ("torchmetrics_tpu.image", "InceptionScore", "feature_extractor=_feat, splits=2",
     _IMG8 + ("from test_lifecycle_sweep import _feat",), "real"),
    ("torchmetrics_tpu.image", "KernelInceptionDistance",
     "feature_extractor=_feat, subsets=2, subset_size=3",
     _IMG8 + ("from test_lifecycle_sweep import _feat",), ("real, real=True", "fake, real=False")),
    ("torchmetrics_tpu.image", "MemorizationInformedFrechetInceptionDistance", "feature_extractor=_feat",
     _IMG8 + ("from test_lifecycle_sweep import _feat",), ("real, real=True", "fake, real=False")),
    ("torchmetrics_tpu.image", "LearnedPerceptualImagePatchSimilarity", "net=_lpips_net",
     _IMG8 + ("from test_lifecycle_sweep import _lpips_net",), "real, fake"),
    ("torchmetrics_tpu.wrappers", "BootStrapper", "BinaryAccuracy(), num_bootstraps=4, seed=42",
     _CLS_SETUP, "preds, target"),
    ("torchmetrics_tpu.wrappers", "MinMaxMetric", "BinaryAccuracy()", _CLS_SETUP, "preds, target"),
    ("torchmetrics_tpu.wrappers", "ClasswiseWrapper", "MulticlassAccuracy(num_classes=3, average=None)",
     ("import jax.numpy as jnp",
      "from torchmetrics_tpu.classification import MulticlassAccuracy",
      "preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])",
      "target = jnp.asarray([0, 1, 2, 0])"), "preds, target"),
    ("torchmetrics_tpu.wrappers", "MultioutputWrapper", "MeanSquaredError(), num_outputs=2",
     ("import jax.numpy as jnp",
      "from torchmetrics_tpu.regression import MeanSquaredError",
      "preds = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])",
      "target = jnp.asarray([[1.0, 1.0], [4.0, 3.0]])"), "preds, target"),
    ("torchmetrics_tpu.wrappers", "MultitaskWrapper",
     '{"cls": BinaryAccuracy(), "reg": MeanSquaredError()}',
     _CLS_SETUP + ("from torchmetrics_tpu.regression import MeanSquaredError",
                   'pd = {"cls": preds, "reg": preds}',
                   'td = {"cls": target, "reg": target.astype(jnp.float32)}'), "pd, td"),
    ("torchmetrics_tpu.wrappers", "Running", "SumMetric(), window=2",
     ("import jax.numpy as jnp", "from torchmetrics_tpu.aggregation import SumMetric",
      "values = jnp.asarray([1.0, 2.0, 3.0])"), "values"),
]

CASES = _collect_cases() + [
    pytest.param(mod, cls, ctor, tuple(setup), upd, id=cls) for mod, cls, ctor, setup, upd in EXTRA_CASES
]

# stochastic wrappers resample per update (RNG advances across calls, like the
# reference's global-RNG bootstrap), so reset+update is not value-reproducible
STOCHASTIC = {"BootStrapper"}


def _build(module_name, cls_name, ctor, setup, upd):
    ns = {}
    lines = [f"from {module_name} import {cls_name}"] + list(setup)
    if cls_name in _TEXT_FLAT:
        lines += ['preds = ["this is the answer"]', 'target = ["this was the answer"]']
    elif cls_name == "Perplexity":
        lines += ["preds = jnp.full((1, 4, 6), 1 / 6)", "target = jnp.asarray([[0, 1, 2, 3]])"]
    elif cls_name == "SQuAD":
        lines += reg.FN_SETUP["squad"]
    lines.append(f"m = {cls_name}({ctor})")
    for ln in lines:
        exec(ln, ns)
    return ns, upd


def _tree_allclose(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        # rtol sits just above float32 fusion-reassociation noise: the eager
        # stateful path now executes COMPILED (ops/executor.py), so modular vs
        # functional comparisons legitimately differ by XLA reduction-order
        # rounding — dB-scaled metrics (SDR) amplify it to ~2e-5 relative
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=3e-5, atol=1e-6)


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", CASES)
def test_lifecycle(module_name, cls_name, ctor, setup, upd):
    ns, upd = _build(module_name, cls_name, ctor, setup, upd)
    m = ns["m"]
    rounds = (upd,) if isinstance(upd, str) else upd

    def do_update(metric):
        nsx = dict(ns); nsx["m"] = metric
        for r in rounds:
            exec(f"m.update({r})", nsx)

    # 1. repeated update + compute
    do_update(m)
    v1 = m.compute()
    do_update(m)
    v2 = m.compute()

    # 2. pickle round-trip preserves the computed value
    m2 = pickle.loads(pickle.dumps(m))
    _tree_allclose(m2.compute(), v2)

    # 3. clone is independent: updating the clone leaves the original unchanged
    do_update(m.clone())
    _tree_allclose(m.compute(), v2)

    # 4. reset + single update reproduces the first value (stochastic
    # resamplers advance their RNG per call and are exempt)
    if cls_name not in STOCHASTIC:
        m.reset()
        do_update(m)
        _tree_allclose(m.compute(), v1)
