"""Kernel-layer suite (ISSUE 11): backend dispatch seam, interpret-mode
parity, and the fused classification megakernel.

Contracts proven here:

- **Registry**: every registered kernel carries a TPU (Mosaic) body, a Triton
  (GPU) lowering and a pure-XLA reference fallback; the static check in
  tests/test_static_checks.py pins every ``pallas_call`` site to this
  registry and this parity suite.
- **Parity**: every Pallas body (both lowerings) runs ``interpret=True`` on
  CPU against its reference body — exact for integer-count kernels, ulp-tight
  for float contractions.
- **Megakernel**: an accuracy + confusion-matrix + stat-scores collection
  lands every accumulator from ONE scatter-accumulate launch
  (jaxpr-verified, counter-verified) and is bit-exact vs the unfused path in
  step AND deferred modes, plain AND laned — including sentinel/poison rows
  diverted by the PR 8 device row screen inside the same dispatch.
- **Cache partition**: the executor's persistent key pins backend/device
  kind and the fused flag, so a Triton lowering (or an unfused A/B) can never
  share a persisted executable with the Mosaic one.

Runs on the 8-fake-device CPU mesh from conftest.py.
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, "/root/repo/tests")

from torchmetrics_tpu import Metric, MetricCollection, obs  # noqa: E402
from torchmetrics_tpu.classification import (  # noqa: E402
    BinaryAccuracy,
    BinaryConfusionMatrix,
    BinaryStatScores,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassStatScores,
    MultilabelAccuracy,
    MultilabelConfusionMatrix,
    MultilabelStatScores,
)
from torchmetrics_tpu.ops import fused_classification as fused  # noqa: E402
from torchmetrics_tpu.ops import kernels  # noqa: E402
from torchmetrics_tpu.ops.bincount import (  # noqa: E402
    _wbincount_pallas,
    _wbincount_reference,
    _wbincount_triton,
)
from torchmetrics_tpu.ops.binned_curve import (  # noqa: E402
    _binned_counts_pallas,
    _binned_counts_searchsorted,
    _binned_counts_triton,
)
from torchmetrics_tpu.ops.executor import make_deferred_collection_step  # noqa: E402
from torchmetrics_tpu.ops.sqrtm_kernel import _sqrtm_pallas, _sqrtm_reference, sqrtm_psd  # noqa: E402
from torchmetrics_tpu.ops.ssim_kernel import _windowed_pallas, _windowed_reference  # noqa: E402
from torchmetrics_tpu.ops.topk_kernel import (  # noqa: E402
    _topk_stats_pallas,
    _topk_stats_reference,
    retrieval_topk_stats,
)
from torchmetrics_tpu.testing import faults  # noqa: E402

NUM_CLASSES = 7
BATCH = 96


def _mc_batch(seed=0, batch=BATCH):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(batch, NUM_CLASSES).astype(np.float32)),
        jnp.asarray(rng.randint(0, NUM_CLASSES, batch)),
    )


def _mc_collection(**kw):
    kw.setdefault("executor", False)
    return MetricCollection(
        [
            MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
            MulticlassConfusionMatrix(num_classes=NUM_CLASSES, validate_args=False),
            MulticlassStatScores(num_classes=NUM_CLASSES, validate_args=False),
        ],
        **kw,
    )


def _assert_tree_equal(a, b, msg=""):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=f"{msg}{k}")


# ---------------------------------------------------------------- registry
class TestRegistry:
    def test_every_kernel_has_three_bodies(self):
        reg = kernels.registered_kernels()
        assert {"bincount", "binned_curve", "ssim_windows", "retrieval_topk_stats", "fid_sqrtm"} <= set(reg)
        for name, spec in reg.items():
            assert spec.reference is not None, name
            assert spec.tpu is not None, f"{name}: no Mosaic body"
            assert spec.triton is not None, f"{name}: no Triton lowering"

    def test_resolve_backend_cpu_and_forced(self, monkeypatch):
        assert kernels.resolve_backend() == "xla"  # CPU CI
        monkeypatch.setenv(kernels.BACKEND_ENV, "triton")
        assert kernels.resolve_backend() == "triton"
        monkeypatch.setenv(kernels.BACKEND_ENV, "tpu")
        assert kernels.resolve_backend() == "tpu"

    def test_gate_min_n_and_extent_env_overrides(self, monkeypatch):
        # force the tpu gate table without running Mosaic: min_n override is
        # high, so the decision falls back to the reference body with the
        # gate reason recorded — the bench's path-attribution contract
        monkeypatch.setenv(kernels.BACKEND_ENV, "tpu")
        monkeypatch.setenv(kernels.MIN_N_ENV, str(1 << 30))
        kernels.reset_gate_log()
        out = kernels.dispatch(
            "bincount", jnp.asarray([0, 1, 1]), jnp.ones((1, 3)), 4, n=3, extent=4
        )
        assert out.shape == (1, 4)
        gate = kernels.gate_snapshot()["bincount"]
        assert gate["path"] == "xla" and "below min_n" in gate["reason"]

        monkeypatch.delenv(kernels.MIN_N_ENV)
        monkeypatch.setenv(kernels.MAX_EXTENT_ENV, "2")
        kernels.reset_gate_log()
        # n clears the registered min_n so only the extent gate can fire
        kernels.dispatch("bincount", jnp.asarray([0, 1, 1]), jnp.ones((1, 3)), 4, n=1 << 20, extent=4)
        gate = kernels.gate_snapshot()["bincount"]
        assert gate["path"] == "xla" and "above max_extent" in gate["reason"]

    def test_gate_log_rides_executor_status(self):
        kernels.reset_gate_log()
        m = MulticlassConfusionMatrix(num_classes=3, validate_args=False)
        m.update(jnp.asarray([0, 1, 2, 1]), jnp.asarray([0, 1, 2, 2]))
        status = m.executor_status["kernels"]
        assert "bincount" in status
        assert status["bincount"]["path"] == "xla"  # CPU CI: reference body
        assert status["bincount"]["selections"]["xla"] >= 1

    def test_kernel_counters_flow_to_obs(self):
        before = obs.counters_snapshot().get("kernels.xla_fallbacks", 0)
        kernels.dispatch("bincount", jnp.asarray([0, 1]), jnp.ones((1, 2)), 2, n=2, extent=2)
        after = obs.counters_snapshot().get("kernels.xla_fallbacks", 0)
        assert after == before + 1


# ------------------------------------------------------------------ parity
class TestInterpretParity:
    """Every registered kernel body, interpret=True on CPU vs its reference."""

    def test_bincount_mosaic_and_triton(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(-5, 300, 4000))  # includes out-of-range
        w = jnp.asarray(rng.rand(3, 4000).astype(np.float32))
        ref = _wbincount_reference(x, w, 290)
        np.testing.assert_allclose(
            np.asarray(_wbincount_pallas(x, w, 290, interpret=True)), np.asarray(ref), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(_wbincount_triton(x, w, 290, interpret=True)), np.asarray(ref), rtol=1e-5
        )

    def test_bincount_integer_counts_exact(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randint(0, 50, 3000))
        w = jnp.ones((1, 3000), jnp.float32)
        ref = _wbincount_reference(x, w, 50)
        np.testing.assert_array_equal(
            np.asarray(_wbincount_pallas(x, w, 50, interpret=True)), np.asarray(ref)
        )
        np.testing.assert_array_equal(
            np.asarray(_wbincount_triton(x, w, 50, interpret=True)), np.asarray(ref)
        )

    def test_binned_curve_mosaic_and_triton(self):
        rng = np.random.RandomState(2)
        p = jnp.asarray(rng.rand(3000).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 2, 3000))
        v = jnp.asarray((rng.rand(3000) > 0.1).astype(np.float32))
        thr = jnp.linspace(0, 1, 37)
        ref = _binned_counts_searchsorted(p, t, v, thr)
        np.testing.assert_array_equal(
            np.asarray(_binned_counts_pallas(p, t, v, thr, interpret=True)), np.asarray(ref)
        )
        np.testing.assert_array_equal(
            np.asarray(_binned_counts_triton(p, t, v, thr, interpret=True)), np.asarray(ref)
        )

    def test_ssim_windows(self):
        rng = np.random.RandomState(3)
        from torchmetrics_tpu.functional.image.utils import _band_matrix, _gaussian

        x = jnp.asarray(rng.rand(10, 44, 52).astype(np.float32))
        bh = _band_matrix(_gaussian(11, 1.5), 34)
        bw = _band_matrix(_gaussian(11, 1.5), 42)
        ref = _windowed_reference(x, bh, bw)
        got = _windowed_pallas(x, bh, bw, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-6, atol=2e-6)

    def test_retrieval_topk_stats(self):
        rng = np.random.RandomState(4)
        t = jnp.asarray(rng.randint(0, 2, (37, 53)).astype(np.float32))
        c = jnp.asarray(rng.randint(1, 54, 37).astype(np.int32))
        for k in (-1, 1, 5, 200):
            ref = _topk_stats_reference(t, c, k)
            got = _topk_stats_pallas(t, c, k, interpret=True)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(ref), err_msg=f"k={k}")

    def test_fid_sqrtm(self):
        """The "fid_sqrtm" Newton–Schulz body vs the exact eigh reference on a
        covariance-shaped PSD input (ISSUE 12 satellite — the last PR 11
        kernel leftover). The iteration is a documented approximation, so the
        tolerance is looser than the exact-count kernels; sqrt(A) @ sqrt(A)
        must also reconstruct A (the defining property, conditioning-robust)."""
        rng = np.random.RandomState(6)
        feats = rng.randn(200, 48).astype(np.float32)
        sigma = jnp.asarray(np.cov(feats, rowvar=False).astype(np.float32))
        ref = _sqrtm_reference(sigma)
        got = _sqrtm_pallas(sigma, interpret=True)
        scale = float(jnp.abs(ref).max())
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=5e-3 * scale)
        recon = np.asarray(got) @ np.asarray(got)
        np.testing.assert_allclose(recon, np.asarray(sigma), atol=5e-3 * float(jnp.abs(sigma).max()))
        # the dispatch wrapper serves the exact reference on CPU (gate closed)
        kernels.reset_gate_log()
        out = sqrtm_psd(sigma)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6)
        assert kernels.gate_snapshot()["fid_sqrtm"]["path"] == "xla"

    def test_fid_sqrtm_rank_deficient_reference(self):
        """The reference body stays NaN-free on the rank-deficient covariance
        a small sample count produces (the regression eigh replaced NS for —
        the gate keeps eigh wherever XLA serves)."""
        rng = np.random.RandomState(7)
        feats = rng.randn(3, 32).astype(np.float32)  # rank <= 2 covariance
        sigma = jnp.asarray(np.cov(feats, rowvar=False).astype(np.float32))
        out = np.asarray(sqrtm_psd(sigma))
        assert np.isfinite(out).all()

    def test_topk_shared_result_memo(self):
        rng = np.random.RandomState(5)
        t = jnp.asarray(rng.randint(0, 2, (8, 16)).astype(np.float32))
        c = jnp.full((8,), 16, jnp.int32)
        before = obs.counters_snapshot().get("kernels.fused_reuses", 0)
        a = retrieval_topk_stats(t, c, 3)
        b = retrieval_topk_stats(t, c, 3)  # identical arrays -> memo hit
        assert a is b
        assert obs.counters_snapshot().get("kernels.fused_reuses", 0) == before + 1


# ------------------------------------------------- fused classification core
class TestMegakernelExactness:
    """Bit-exact fused vs unfused for every task family and dispatch mode."""

    def _run_pair(self, build, drive, monkeypatch):
        values = {}
        for flag in ("1", "0"):
            monkeypatch.setenv(fused.FUSED_ENV, flag)
            kernels.clear_shared_results()
            obj = build()
            drive(obj)
            values[flag] = obj.compute()
        if isinstance(values["1"], dict):
            _assert_tree_equal(values["1"], values["0"])
        else:
            np.testing.assert_array_equal(np.asarray(values["1"]), np.asarray(values["0"]))

    @pytest.mark.parametrize("ignore_index", [None, 3])
    def test_multiclass_family(self, monkeypatch, ignore_index):
        preds, target = _mc_batch(7)

        def build():
            return MetricCollection(
                [
                    MulticlassAccuracy(num_classes=NUM_CLASSES, ignore_index=ignore_index, validate_args=False),
                    MulticlassConfusionMatrix(num_classes=NUM_CLASSES, ignore_index=ignore_index, validate_args=False),
                    MulticlassStatScores(num_classes=NUM_CLASSES, ignore_index=ignore_index, validate_args=False),
                ],
                executor=False,
            )

        self._run_pair(build, lambda c: [c.update(preds, target) for _ in range(3)], monkeypatch)

    def test_binary_family(self, monkeypatch):
        rng = np.random.RandomState(8)
        preds = jnp.asarray(rng.rand(200).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 200))

        def build():
            return MetricCollection(
                [BinaryAccuracy(validate_args=False), BinaryConfusionMatrix(validate_args=False), BinaryStatScores(validate_args=False)],
                executor=False,
            )

        self._run_pair(build, lambda c: [c.update(preds, target) for _ in range(2)], monkeypatch)

    def test_multilabel_family(self, monkeypatch):
        rng = np.random.RandomState(9)
        preds = jnp.asarray(rng.rand(100, 5).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, (100, 5)))

        def build():
            return MetricCollection(
                [
                    MultilabelAccuracy(num_labels=5, validate_args=False),
                    MultilabelConfusionMatrix(num_labels=5, validate_args=False),
                    MultilabelStatScores(num_labels=5, validate_args=False),
                ],
                executor=False,
            )

        self._run_pair(build, lambda c: [c.update(preds, target) for _ in range(2)], monkeypatch)

    def test_executor_fused_dispatch(self, monkeypatch):
        preds, target = _mc_batch(10)

        def drive(coll):
            for _ in range(3):
                coll.update(preds, target)

        self._run_pair(lambda: _mc_collection(executor=True), drive, monkeypatch)

    def test_forward_batch_values(self, monkeypatch):
        preds, target = _mc_batch(11)
        out = {}
        for flag in ("1", "0"):
            monkeypatch.setenv(fused.FUSED_ENV, flag)
            kernels.clear_shared_results()
            coll = _mc_collection(executor=True)
            out[flag] = coll(preds, target)
        _assert_tree_equal(out["1"], out["0"])

    def test_samplewise_and_topk_stay_unfused(self, monkeypatch):
        monkeypatch.setenv(fused.FUSED_ENV, "1")
        assert not MulticlassStatScores(
            num_classes=NUM_CLASSES, multidim_average="samplewise", validate_args=False
        )._fused_active()
        assert not MulticlassStatScores(
            num_classes=NUM_CLASSES, top_k=2, validate_args=False
        )._fused_active()
        monkeypatch.setenv(fused.FUSED_ENV, "0")
        assert not MulticlassStatScores(num_classes=NUM_CLASSES, validate_args=False)._fused_active()


# ------------------------------------------- one-launch + counter verification
class TestMegakernelFusion:
    def test_one_scatter_in_fused_collection_jaxpr(self, monkeypatch):
        """The compiled collection update contains exactly ONE
        scatter-accumulate serving accuracy + confusion + stat-scores."""
        preds, target = _mc_batch(12)

        def scatters(flag):
            monkeypatch.setenv(fused.FUSED_ENV, flag)
            kernels.clear_shared_results()
            coll = _mc_collection()
            coll.resolve_compute_groups(preds, target)
            jaxpr = str(jax.make_jaxpr(coll.functional_update)(coll.functional_init(), preds, target))
            return jaxpr.count("scatter-add")

        assert scatters("1") == 1
        assert scatters("0") == 2  # one per counting group, unfused

    def test_memo_counters_one_build_two_reuses(self, monkeypatch):
        monkeypatch.setenv(fused.FUSED_ENV, "1")
        preds, target = _mc_batch(13)
        kernels.clear_shared_results()
        coll = _mc_collection()
        coll.resolve_compute_groups(preds, target)
        before = obs.counters_snapshot()
        jax.make_jaxpr(coll.functional_update)(coll.functional_init(), preds, target)
        after = obs.counters_snapshot()
        # 2 counting groups in one trace: 1 shared build + 1 reuse
        assert after.get("kernels.fused_builds", 0) - before.get("kernels.fused_builds", 0) == 1
        assert after.get("kernels.fused_reuses", 0) - before.get("kernels.fused_reuses", 0) == 1

    def test_memo_rejects_different_arrays(self, monkeypatch):
        monkeypatch.setenv(fused.FUSED_ENV, "1")
        kernels.clear_shared_results()
        p1, t1 = _mc_batch(14)
        p2, t2 = _mc_batch(15)
        a = fused.multiclass_confusion_counts(p1, t1, NUM_CLASSES, None)
        b = fused.multiclass_confusion_counts(p2, t2, NUM_CLASSES, None)
        assert a is not b
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_memo_keyed_on_config(self, monkeypatch):
        monkeypatch.setenv(fused.FUSED_ENV, "1")
        kernels.clear_shared_results()
        p, t = _mc_batch(16)
        a = fused.multiclass_confusion_counts(p, t, NUM_CLASSES, None)
        b = fused.multiclass_confusion_counts(p, t, NUM_CLASSES, 3)  # different ignore_index
        assert a is not b


# -------------------------------------------------------- deferred + laned
class TestMegakernelComposition:
    """Fused counts under shard_map (deferred) and vmap (laned), composing
    with the five reduction families and the PR 8 device row screen."""

    NUM_DEVICES = 8

    def _mesh(self):
        return Mesh(np.array(jax.devices()[: self.NUM_DEVICES]), ("batch",))

    def test_deferred_epoch_bit_exact(self, monkeypatch):
        mesh = self._mesh()
        batches = [_mc_batch(20 + i, batch=64) for i in range(3)]
        vals = {}
        for flag in ("1", "0"):
            monkeypatch.setenv(fused.FUSED_ENV, flag)
            kernels.clear_shared_results()
            coll = _mc_collection(reduce="deferred")
            coll.resolve_compute_groups(*batches[0])
            deferred = make_deferred_collection_step(coll, mesh, axis_name="batch")
            st = deferred.init_states()
            for lg, tg in batches:
                st = deferred.local_step(
                    st,
                    jax.device_put(lg, NamedSharding(mesh, P("batch"))),
                    jax.device_put(tg, NamedSharding(mesh, P("batch"))),
                )
            vals[flag] = deferred.reduce(st)
        _assert_tree_equal(vals["1"], vals["0"], msg="deferred:")

    def test_laned_all_families_bit_exact(self, monkeypatch):
        """A laned collection mixing the fused classification family with
        mean/max-reduced aggregator states: per-session values bit-exact
        fused vs unfused (cat/list states take the eager lane loop and are
        covered by the plain-mode tests)."""
        from torchmetrics_tpu.aggregation import MaxMetric, MeanMetric

        def build():
            return MetricCollection(
                {
                    "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
                    "conf": MulticlassConfusionMatrix(num_classes=NUM_CLASSES, validate_args=False),
                    "stat": MulticlassStatScores(num_classes=NUM_CLASSES, validate_args=False),
                },
                executor=False,
            )

        batches = {sid: _mc_batch(30 + i, batch=32) for i, sid in enumerate("abcd")}
        vals = {}
        for flag in ("1", "0"):
            monkeypatch.setenv(fused.FUSED_ENV, flag)
            kernels.clear_shared_results()
            laned = build().laned(capacity=8)
            for _ in range(2):
                laned.update_sessions([(sid, b) for sid, b in batches.items()])
            vals[flag] = {sid: laned.compute_session(sid) for sid in batches}
        for sid in batches:
            _assert_tree_equal(vals["1"][sid], vals["0"][sid], msg=f"lane {sid}:")

    def test_laned_poison_rows_through_fused_row_screen(self, monkeypatch):
        """Sentinel/poison rows: one tenant ships NaN batches every round with
        the device row screen active — its rows are diverted at the scatter
        inside the same dispatch that runs the fused counts, and every OTHER
        session's compute stays bit-exact vs a fault-free fused run."""
        monkeypatch.setenv(fused.FUSED_ENV, "1")

        def build():
            return MetricCollection(
                [
                    MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False),
                    MulticlassConfusionMatrix(num_classes=NUM_CLASSES, validate_args=False),
                    MulticlassStatScores(num_classes=NUM_CLASSES, validate_args=False),
                ],
                executor=False,
            ).laned(capacity=8, on_lane_fault="quarantine")

        batches = {sid: _mc_batch(40 + i, batch=32) for i, sid in enumerate("abcd")}

        kernels.clear_shared_results()
        clean = build()
        for _ in range(3):
            clean.update_sessions([(sid, b) for sid, b in batches.items() if sid != "a"])
        clean_vals = {sid: clean.compute_session(sid) for sid in "bcd"}

        kernels.clear_shared_results()
        stormy = build()
        with faults.poison_session(stormy, "a", mode="nan", frac=1.0):
            for _ in range(3):
                stormy.update_sessions([(sid, b) for sid, b in batches.items()])
        for sid in "bcd":
            _assert_tree_equal(stormy.compute_session(sid), clean_vals[sid], msg=f"lane {sid}:")


# ------------------------------------------------------- cache-key partition
class TestCacheKeyPartition:
    def test_backend_fingerprint_partitions_key(self, monkeypatch):
        """A Triton (GPU) lowering lands in its own persistent-cache
        partition: the executor key embeds backend/device_kind."""
        from torchmetrics_tpu.ops import compile_cache

        m = MulticlassAccuracy(num_classes=3, validate_args=False)
        m.update(jnp.asarray([0, 1, 2]), jnp.asarray([0, 1, 1]))
        ex = m._get_executor()
        key = ("u", None, (), None, None, ())
        cpu_desc = ex._key_desc(key)
        assert compile_cache.backend_fingerprint() in cpu_desc
        monkeypatch.setattr(
            compile_cache, "backend_fingerprint", lambda: "gpu/NVIDIA H100"
        )
        gpu_desc = ex._key_desc(key)
        assert gpu_desc != cpu_desc and "gpu/NVIDIA H100" in gpu_desc

    def test_fused_flag_partitions_key(self, monkeypatch):
        """fused-on and fused-off traces can never share a persisted
        executable: the flag rides _trace_config into the owner descriptor."""
        descs = {}
        for flag in ("1", "0"):
            monkeypatch.setenv(fused.FUSED_ENV, flag)
            coll = _mc_collection(executor=True)
            coll.resolve_compute_groups(*_mc_batch(50))
            descs[flag] = coll._get_executor()._owner_desc()
        assert descs["1"] != descs["0"]
        assert "fused=1" in descs["1"] and "fused=0" in descs["0"]
