"""Regression metric parity tests vs sklearn/scipy."""
import functools
import sys

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats
from sklearn.metrics import (
    d2_tweedie_score,
    explained_variance_score as sk_ev,
    mean_absolute_error as sk_mae,
    mean_absolute_percentage_error as sk_mape,
    mean_squared_error as sk_mse,
    mean_squared_log_error as sk_msle,
    r2_score as sk_r2,
)

import torchmetrics_tpu.functional as F
from torchmetrics_tpu import (
    ConcordanceCorrCoef,
    CosineSimilarity,
    CriticalSuccessIndex,
    ExplainedVariance,
    KendallRankCorrCoef,
    KLDivergence,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MinkowskiDistance,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)

sys.path.insert(0, "/root/repo/tests")
from helpers.testers import MetricTester  # noqa: E402

NUM_BATCHES, BATCH_SIZE = 4, 32
rng = np.random.RandomState(11)
PREDS = rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) * 10
TARGET = (PREDS + rng.randn(NUM_BATCHES, BATCH_SIZE).astype(np.float32)).clip(0.01)
PREDS = PREDS.clip(0.01)


class TestBasicErrors(MetricTester):
    def test_mae(self):
        self.run_functional_metric_test(PREDS, TARGET, F.mean_absolute_error, lambda p, t: sk_mae(t.reshape(-1), p.reshape(-1)))
        self.run_class_metric_test(PREDS, TARGET, MeanAbsoluteError, lambda p, t: sk_mae(t.reshape(-1), p.reshape(-1)), ddp=True)

    def test_mse(self):
        self.run_functional_metric_test(PREDS, TARGET, F.mean_squared_error, lambda p, t: sk_mse(t.reshape(-1), p.reshape(-1)))
        self.run_class_metric_test(PREDS, TARGET, MeanSquaredError, lambda p, t: sk_mse(t.reshape(-1), p.reshape(-1)), ddp=True)

    def test_rmse(self):
        self.run_class_metric_test(
            PREDS,
            TARGET,
            functools.partial(MeanSquaredError, squared=False),
            lambda p, t: np.sqrt(sk_mse(t.reshape(-1), p.reshape(-1))),
            ddp=True,
        )

    def test_msle(self):
        self.run_functional_metric_test(PREDS, TARGET, F.mean_squared_log_error, lambda p, t: sk_msle(t.reshape(-1), p.reshape(-1)))
        self.run_class_metric_test(PREDS, TARGET, MeanSquaredLogError, lambda p, t: sk_msle(t.reshape(-1), p.reshape(-1)), ddp=False)

    def test_mape(self):
        self.run_functional_metric_test(
            PREDS, TARGET, F.mean_absolute_percentage_error, lambda p, t: sk_mape(t.reshape(-1), p.reshape(-1)), atol=1e-4
        )
        self.run_class_metric_test(
            PREDS, TARGET, MeanAbsolutePercentageError, lambda p, t: sk_mape(t.reshape(-1), p.reshape(-1)), ddp=True, atol=1e-4
        )

    def test_smape(self):
        def ref(p, t):
            p, t = p.reshape(-1), t.reshape(-1)
            return np.mean(2 * np.abs(p - t) / (np.abs(p) + np.abs(t)))

        self.run_functional_metric_test(PREDS, TARGET, F.symmetric_mean_absolute_percentage_error, ref, atol=1e-4)

    def test_wmape(self):
        def ref(p, t):
            p, t = p.reshape(-1), t.reshape(-1)
            return np.abs(p - t).sum() / np.abs(t).sum()

        self.run_functional_metric_test(PREDS, TARGET, F.weighted_mean_absolute_percentage_error, ref, atol=1e-4)
        self.run_class_metric_test(PREDS, TARGET, WeightedMeanAbsolutePercentageError, ref, ddp=True, atol=1e-4)

    def test_logcosh(self):
        def ref(p, t):
            d = p.reshape(-1) - t.reshape(-1)
            return np.mean(np.log(np.cosh(d)))

        self.run_functional_metric_test(PREDS, TARGET, F.log_cosh_error, ref, atol=1e-4)
        self.run_class_metric_test(PREDS, TARGET, LogCoshError, ref, ddp=False, atol=1e-4)

    def test_minkowski(self):
        def ref(p, t):
            return (np.abs(p.reshape(-1) - t.reshape(-1)) ** 3).sum() ** (1 / 3)

        self.run_functional_metric_test(PREDS, TARGET, functools.partial(F.minkowski_distance, p=3), ref, atol=1e-3)

    @pytest.mark.parametrize("power", [0.0, 1.0, 2.0, 1.5])
    def test_tweedie(self, power):
        def ref(p, t):
            p, t = p.reshape(-1).astype(np.float64), t.reshape(-1).astype(np.float64)
            if power == 0:
                return np.mean((p - t) ** 2)
            if power == 1:
                return np.mean(2 * (t * np.log(t / p) + p - t))
            if power == 2:
                return np.mean(2 * (np.log(p / t) + t / p - 1))
            return np.mean(
                2 * (t ** (2 - power) / ((1 - power) * (2 - power)) - t * p ** (1 - power) / (1 - power) + p ** (2 - power) / (2 - power))
            )

        self.run_functional_metric_test(
            PREDS, TARGET, functools.partial(F.tweedie_deviance_score, power=power), ref, atol=1e-3
        )

    def test_csi(self):
        def ref(p, t):
            pb, tb = p >= 5.0, t >= 5.0
            hits = (pb & tb).sum()
            return hits / (hits + (~pb & tb).sum() + (pb & ~tb).sum())

        self.run_functional_metric_test(PREDS, TARGET, functools.partial(F.critical_success_index, threshold=5.0), ref)
        self.run_class_metric_test(PREDS, TARGET, functools.partial(CriticalSuccessIndex, threshold=5.0), ref, ddp=True)


class TestCorrelations(MetricTester):
    def test_pearson_functional(self):
        self.run_functional_metric_test(
            PREDS, TARGET, F.pearson_corrcoef, lambda p, t: scipy.stats.pearsonr(t.reshape(-1), p.reshape(-1))[0], atol=1e-4
        )

    def test_pearson_class_streaming(self):
        m = PearsonCorrCoef()
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref = scipy.stats.pearsonr(TARGET.reshape(-1), PREDS.reshape(-1))[0]
        assert abs(float(m.compute()) - ref) < 1e-4

    def test_pearson_chan_merge(self):
        # per-rank states merged by _final_aggregation must equal global
        from torchmetrics_tpu.functional.regression.pearson import _final_aggregation

        m = PearsonCorrCoef()
        states = []
        for i in range(NUM_BATCHES):
            st = m.functional_update(m.init_state(), jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
            states.append(st)
        stacked = {k: jnp.stack([s[k] for s in states]) for k in states[0]}
        _, _, var_x, var_y, corr_xy, nb = _final_aggregation(
            stacked["mean_x"], stacked["mean_y"], stacked["var_x"], stacked["var_y"], stacked["corr_xy"], stacked["n_total"]
        )
        from torchmetrics_tpu.functional.regression.pearson import _pearson_corrcoef_compute

        merged = float(_pearson_corrcoef_compute(var_x, var_y, corr_xy, nb))
        ref = scipy.stats.pearsonr(TARGET.reshape(-1), PREDS.reshape(-1))[0]
        assert abs(merged - ref) < 1e-4

    def test_spearman(self):
        self.run_functional_metric_test(
            PREDS, TARGET, F.spearman_corrcoef, lambda p, t: scipy.stats.spearmanr(t.reshape(-1), p.reshape(-1))[0], atol=1e-4
        )
        self.run_class_metric_test(
            PREDS, TARGET, SpearmanCorrCoef, lambda p, t: scipy.stats.spearmanr(t.reshape(-1), p.reshape(-1))[0], ddp=True, atol=1e-4
        )

    def test_kendall(self):
        self.run_functional_metric_test(
            PREDS, TARGET, F.kendall_rank_corrcoef, lambda p, t: scipy.stats.kendalltau(t.reshape(-1), p.reshape(-1))[0], atol=1e-4
        )
        self.run_class_metric_test(
            PREDS, TARGET, KendallRankCorrCoef, lambda p, t: scipy.stats.kendalltau(t.reshape(-1), p.reshape(-1))[0], ddp=False, atol=1e-4
        )

    def test_concordance(self):
        def ref_ccc(p, t):
            p, t = p.reshape(-1), t.reshape(-1)
            pearson = scipy.stats.pearsonr(t, p)[0]
            return (2 * pearson * p.std(ddof=1) * t.std(ddof=1)) / (p.var(ddof=1) + t.var(ddof=1) + (p.mean() - t.mean()) ** 2)

        self.run_functional_metric_test(PREDS, TARGET, F.concordance_corrcoef, ref_ccc, atol=1e-4)
        m = ConcordanceCorrCoef()
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        assert abs(float(m.compute()) - ref_ccc(PREDS, TARGET)) < 1e-4

    def test_r2(self):
        self.run_functional_metric_test(PREDS, TARGET, F.r2_score, lambda p, t: sk_r2(t.reshape(-1), p.reshape(-1)), atol=1e-4)
        self.run_class_metric_test(PREDS, TARGET, R2Score, lambda p, t: sk_r2(t.reshape(-1), p.reshape(-1)), ddp=True, atol=1e-4)

    def test_explained_variance(self):
        self.run_functional_metric_test(PREDS, TARGET, F.explained_variance, lambda p, t: sk_ev(t.reshape(-1), p.reshape(-1)), atol=1e-4)
        self.run_class_metric_test(PREDS, TARGET, ExplainedVariance, lambda p, t: sk_ev(t.reshape(-1), p.reshape(-1)), ddp=True, atol=1e-4)


class TestMisc(MetricTester):
    def test_cosine_similarity(self):
        p2 = PREDS.reshape(NUM_BATCHES, 8, 4)
        t2 = TARGET.reshape(NUM_BATCHES, 8, 4)

        def ref(p, t):
            sims = (p * t).sum(-1) / (np.linalg.norm(p, axis=-1) * np.linalg.norm(t, axis=-1))
            return sims.sum()

        self.run_functional_metric_test(p2, t2, F.cosine_similarity, ref, atol=1e-4)

    def test_kl_divergence(self):
        p = rng.rand(NUM_BATCHES, BATCH_SIZE, 5).astype(np.float32)
        q = rng.rand(NUM_BATCHES, BATCH_SIZE, 5).astype(np.float32)

        def ref(pp, qq):
            pp = pp / pp.sum(-1, keepdims=True)
            qq = qq / qq.sum(-1, keepdims=True)
            return (pp * np.log(pp / qq)).sum(-1).mean()

        self.run_functional_metric_test(p, q, F.kl_divergence, ref, atol=1e-4)

    def test_pairwise(self):
        from scipy.spatial.distance import cdist

        x = rng.rand(10, 4).astype(np.float32)
        y = rng.rand(7, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(F.pairwise_euclidean_distance(jnp.asarray(x), jnp.asarray(y))), cdist(x, y), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(F.pairwise_manhattan_distance(jnp.asarray(x), jnp.asarray(y))), cdist(x, y, "cityblock"), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(F.pairwise_cosine_similarity(jnp.asarray(x), jnp.asarray(y))), 1 - cdist(x, y, "cosine"), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(F.pairwise_minkowski_distance(jnp.asarray(x), jnp.asarray(y), exponent=3)),
            cdist(x, y, "minkowski", p=3),
            atol=1e-4,
        )
        np.testing.assert_allclose(np.asarray(F.pairwise_linear_similarity(jnp.asarray(x), jnp.asarray(y))), x @ y.T, atol=1e-4)

    def test_minkowski_class(self):
        m = MinkowskiDistance(p=3)
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(PREDS[i]), jnp.asarray(TARGET[i]))
        ref = (np.abs(PREDS - TARGET) ** 3).sum() ** (1 / 3)
        assert abs(float(m.compute()) - ref) < 1e-2

    def test_kldiv_class(self):
        p = rng.rand(64, 5).astype(np.float32)
        q = rng.rand(64, 5).astype(np.float32)
        m = KLDivergence()
        m.update(jnp.asarray(p), jnp.asarray(q))
        pp = p / p.sum(-1, keepdims=True)
        qq = q / q.sum(-1, keepdims=True)
        ref = (pp * np.log(pp / qq)).sum(-1).mean()
        assert abs(float(m.compute()) - ref) < 1e-4
