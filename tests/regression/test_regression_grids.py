"""Regression parameter-grid parity vs the reference oracle.

Depth complement to the registry sweeps for the regression domain: enumerates
the reference's own test axes (reference tests/unittests/regression/
test_mean_error.py, test_r2.py, test_explained_variance.py,
test_tweedie_deviance.py, test_kl_divergence.py) — ``squared``/``num_outputs``,
``adjusted``/``multioutput``, Tweedie ``power``, KL ``log_prob``/``reduction``,
Minkowski ``p`` — against live CPU torch.
"""
import sys

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # oracle parameter grids; run with --runslow

sys.path.insert(0, "/root/repo/tests")

from helpers.reference import load_reference_torchmetrics  # noqa: E402

load_reference_torchmetrics()

import torch  # noqa: E402
import torchmetrics.functional.regression as RR  # noqa: E402

import torchmetrics_tpu.functional.regression as OR  # noqa: E402

N, D = 64, 3
rng = np.random.RandomState(123)
PREDS = rng.randn(N, D).astype(np.float32)
TARGET = (PREDS + 0.3 * rng.randn(N, D)).astype(np.float32)
PREDS_1D = PREDS[:, 0]
TARGET_1D = TARGET[:, 0]
POS_PREDS = np.abs(PREDS) + 0.1
POS_TARGET = np.abs(TARGET) + 0.1
POS_PREDS_1D = POS_PREDS[:, 0]
POS_TARGET_1D = POS_TARGET[:, 0]
PROBS = rng.dirichlet(np.ones(D), N).astype(np.float32)
PROBS2 = rng.dirichlet(np.ones(D), N).astype(np.float32)


def _both(name, args, kwargs, atol=1e-5):
    ours = getattr(OR, name)(*[jnp.asarray(a) for a in args], **kwargs)
    theirs = getattr(RR, name)(*[torch.from_numpy(np.asarray(a)) for a in args], **kwargs)
    np.testing.assert_allclose(
        np.asarray(ours, dtype=np.float64),
        theirs.numpy().astype(np.float64),
        atol=atol, rtol=1e-4, err_msg=f"{name} {kwargs}",
    )


@pytest.mark.parametrize("squared", [True, False])
@pytest.mark.parametrize("num_outputs", [1, D])
def test_mse_grid(squared, num_outputs):
    args = (PREDS_1D, TARGET_1D) if num_outputs == 1 else (PREDS, TARGET)
    _both("mean_squared_error", args, {"squared": squared, "num_outputs": num_outputs})


@pytest.mark.parametrize("adjusted", [0, 5])
@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_r2_grid(adjusted, multioutput):
    _both("r2_score", (PREDS, TARGET), {"adjusted": adjusted, "multioutput": multioutput})


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_explained_variance_grid(multioutput):
    _both("explained_variance", (PREDS, TARGET), {"multioutput": multioutput})


@pytest.mark.parametrize("power", [0.0, 1.0, 1.5, 2.0, 3.0])
def test_tweedie_power_grid(power):
    # power in (1,2) needs strictly positive preds & targets; >=2 positive targets
    _both("tweedie_deviance_score", (POS_PREDS_1D, POS_TARGET_1D), {"power": power}, atol=1e-4)


@pytest.mark.parametrize("log_prob", [True, False])
@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_kl_divergence_grid(log_prob, reduction):
    p = np.log(PROBS) if log_prob else PROBS
    q = np.log(PROBS2) if log_prob else PROBS2
    _both("kl_divergence", (p, q), {"log_prob": log_prob, "reduction": reduction})


@pytest.mark.parametrize("p", [1.0, 2.0, 3.0, 4.5])
def test_minkowski_grid(p):
    _both("minkowski_distance", (PREDS_1D, TARGET_1D), {"p": p})


@pytest.mark.parametrize(
    "name",
    [
        "mean_absolute_error", "mean_absolute_percentage_error",
        "symmetric_mean_absolute_percentage_error",
        "weighted_mean_absolute_percentage_error", "log_cosh_error",
        "relative_squared_error",
    ],
)
def test_error_multioutput_default(name):
    args = (POS_PREDS, POS_TARGET)
    _both(name, args, {}, atol=1e-4)


@pytest.mark.parametrize("squared", [True, False])
def test_relative_squared_error_squared(squared):
    _both("relative_squared_error", (PREDS, TARGET), {"squared": squared}, atol=1e-4)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_cosine_similarity_reduction(reduction):
    _both("cosine_similarity", (PREDS, TARGET), {"reduction": reduction})
