"""Registry sweep: every buildable metric class's ``.plot()`` renders.

The reference backs its universal ``.plot()`` claim with a large parametrized
sweep (reference tests/unittests/utilities/test_plot.py); this is the
counterpart here, riding the lifecycle sweep's case registry: build the
metric, update once, call ``.plot()``, and require a live matplotlib
(figure, axes) pair back. Catches plot regressions for value layouts the
dedicated plot tests don't cover (per-class vectors, dict outputs, curve
tuples).
"""
import pathlib
import sys

import matplotlib
import pytest

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from test_lifecycle_sweep import CASES, _build  # noqa: E402

pytestmark = pytest.mark.slow  # registry sweep; run with --runslow

# metrics whose compute() output has no generic single/multi-value rendering;
# each names where its plotting IS covered or why none exists (mirrors the
# reference sweep's own exclusions)
PLOT_SKIP = {
    "MeanAveragePrecision",   # dict incl. per-class arrays; reference plots via its own override
    "MultitaskWrapper",       # dict-of-task dicts; per-task metrics plot individually
    "SQuAD",                  # dict of EM/F1; reference plots the flattened pair the same way
}


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", CASES)
def test_plot_renders(module_name, cls_name, ctor, setup, upd):
    if cls_name in PLOT_SKIP:
        pytest.skip("no generic single/multi-value rendering; see PLOT_SKIP note")
    ns, upd = _build(module_name, cls_name, ctor, setup, upd)
    m = ns["m"]
    rounds = (upd,) if isinstance(upd, str) else upd
    nsx = dict(ns)
    for r in rounds:
        exec(f"m.update({r})", nsx)
    try:
        fig, ax = m.plot()
    except Exception as err:  # pragma: no cover - the assertion message is the point
        raise AssertionError(f"{cls_name}.plot() raised {type(err).__name__}: {err}") from err
    assert fig is not None and ax is not None
    plt.close(fig)
