"""Metrics under XLA auto-SPMD: plain ``jit`` + ``NamedSharding`` inputs.

The shard_map tests drive the EXPLICIT collective path (per-shard update +
declared-reduction sync). This module pins the other TPU-native mode from
SURVEY §2.17: metric updates traced under plain ``jax.jit`` over globally
sharded inputs, where the SPMD partitioner inserts the cross-device
reductions itself — no ``functional_sync`` call, no shard_map. This is how
metrics compose with a pjit training step whose activations already carry
shardings.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from sklearn.metrics import accuracy_score, f1_score, mean_squared_error

from torchmetrics_tpu import MeanMetric, MeanSquaredError, MetricCollection
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
)

N, C = 64, 5
rng = np.random.RandomState(11)
PREDS = rng.randint(0, C, N)
TARGET = rng.randint(0, C, N)


def _shard(mesh, x, spec):
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))


class TestAutoSPMD:
    def test_metric_update_compute_under_jit(self, mesh):
        """Sharded inputs, replicated state: value equals the global oracle."""
        m = MulticlassAccuracy(num_classes=C, average="micro", validate_args=False)
        p = _shard(mesh, PREDS, P("batch"))
        t = _shard(mesh, TARGET, P("batch"))

        step = jax.jit(m.functional_update)
        state = step(m.functional_init(), p, t)
        val = jax.jit(m.functional_compute)(state)
        assert abs(float(val) - accuracy_score(TARGET, PREDS)) < 1e-6
        # the accumulated state is fully replicated — no shard-local residue
        for leaf in jax.tree_util.tree_leaves(state):
            assert leaf.sharding.is_fully_replicated

    def test_multi_step_accumulation(self, mesh):
        m = MeanSquaredError()
        x = rng.randn(4, N).astype(np.float32)
        y = rng.randn(4, N).astype(np.float32)
        step = jax.jit(m.functional_update)
        state = m.functional_init()
        for i in range(4):
            state = step(state, _shard(mesh, x[i], P("batch")), _shard(mesh, y[i], P("batch")))
        val = float(jax.jit(m.functional_compute)(state))
        assert abs(val - mean_squared_error(y.reshape(-1), x.reshape(-1))) < 1e-5

    def test_collection_under_jit(self, mesh):
        coll = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=C, average="micro", validate_args=False),
                "f1": MulticlassF1Score(num_classes=C, validate_args=False),
                "confmat": MulticlassConfusionMatrix(num_classes=C, validate_args=False),
            }
        )
        p = _shard(mesh, PREDS, P("batch"))
        t = _shard(mesh, TARGET, P("batch"))
        coll.resolve_compute_groups(jnp.asarray(PREDS[:8]), jnp.asarray(TARGET[:8]))
        states = jax.jit(coll.functional_update)(coll.functional_init(), p, t)
        res = coll.functional_compute(states)
        assert abs(float(res["acc"]) - accuracy_score(TARGET, PREDS)) < 1e-6
        assert abs(float(res["f1"]) - f1_score(TARGET, PREDS, average="macro")) < 1e-6
        assert int(np.asarray(res["confmat"]).sum()) == N

    def test_2d_sharded_inputs(self, mesh2d):
        """(batch, seq) values sharded over BOTH mesh axes — the long-context
        layout — reduce to the correct global mean under plain jit."""
        m = MeanMetric()
        vals = rng.rand(16, 8).astype(np.float32)
        v = _shard(mesh2d, vals, P("data", "seq"))
        state = jax.jit(m.functional_update)(m.functional_init(), v)
        out = float(jax.jit(m.functional_compute)(state))
        assert abs(out - float(vals.mean())) < 1e-6

    def test_forward_under_jit(self, mesh):
        """functional_forward (state', batch value) traces under jit with
        sharded inputs too."""
        m = MulticlassAccuracy(num_classes=C, average="micro", validate_args=False)
        p = _shard(mesh, PREDS, P("batch"))
        t = _shard(mesh, TARGET, P("batch"))
        fwd = jax.jit(m.functional_forward)
        state, batch_val = fwd(m.functional_init(), p, t)
        assert abs(float(batch_val) - accuracy_score(TARGET, PREDS)) < 1e-6
        val = float(jax.jit(m.functional_compute)(state))
        assert abs(val - accuracy_score(TARGET, PREDS)) < 1e-6


@pytest.fixture(scope="module")
def mesh2d():
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devices, ("data", "seq"))
