"""Registry sweep: wrapper functional paths vs the OO wrappers, per metric class.

For every buildable metric class whose states are mergeable tensors
(sum/mean/max/min reductions, ``full_state_update=False``), wrap it in
``Running`` and ``MinMaxMetric`` and assert the pure
``functional_init/functional_update/functional_compute`` path produces the
same values as the eager OO path over the same update sequence. This is the
breadth check that the wrappers' merge/ring/extrema machinery respects each
metric's actual state layout — a per-class analogue of the merge_states
consistency sweep.
"""
import pathlib
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

import test_lifecycle_sweep as lifecycle  # noqa: E402

pytestmark = pytest.mark.slow

from torchmetrics_tpu.wrappers import MinMaxMetric, Running  # noqa: E402
from torchmetrics_tpu.wrappers.abstract import WrapperMetric, _require_mergeable_tensor_states  # noqa: E402


def _tree_allclose(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        # rtol just above float32 fusion-reassociation noise: the OO side runs
        # COMPILED through the executor (ops/executor.py), so functional-eager
        # vs modular-compiled comparisons carry XLA reduction-order rounding
        # that dB-scaled metrics (SDR) amplify to ~2e-5 relative
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=3e-5, atol=1e-6)


def _eligible_or_skip(metric, cls_name):
    if isinstance(metric, WrapperMetric):
        pytest.skip("wrapping a wrapper is out of scope for this sweep")
    if metric.full_state_update is not False:
        pytest.skip("functional wrapper paths require full_state_update=False")
    try:
        _require_mergeable_tensor_states(metric, "sweep")
    except ValueError:
        pytest.skip("list/'cat'/custom states cannot ride the ring/merge paths")


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", lifecycle.CASES)
def test_running_functional_matches_oo(module_name, cls_name, ctor, setup, upd):
    ns_oo, upd = lifecycle._build(module_name, cls_name, ctor, setup, upd)
    _eligible_or_skip(ns_oo["m"], cls_name)
    ns_fn, _ = lifecycle._build(module_name, cls_name, ctor, setup, upd)
    rounds = (upd,) if isinstance(upd, str) else upd

    oo = Running(ns_oo["m"], window=2)
    fn = Running(ns_fn["m"], window=2)
    state = fn.functional_init()
    for _ in range(3):  # 3 updates > window: the ring must evict the oldest
        for r in rounds:
            nsx = dict(ns_oo)
            nsx["w"] = oo
            exec(f"w.update({r})", nsx)
            nsy = dict(ns_fn)
            nsy["w"], nsy["state"] = fn, state
            exec(f"state = w.functional_update(state, {r})", nsy)
            state = nsy["state"]
    _tree_allclose(fn.functional_compute(state), oo.compute())


@pytest.mark.parametrize("module_name,cls_name,ctor,setup,upd", lifecycle.CASES)
def test_minmax_functional_matches_oo(module_name, cls_name, ctor, setup, upd):
    ns_oo, upd = lifecycle._build(module_name, cls_name, ctor, setup, upd)
    _eligible_or_skip(ns_oo["m"], cls_name)
    # MinMax demands scalar computes (OO _track contract) — probe and skip vectors/dicts
    ns_probe, probe_upd = lifecycle._build(module_name, cls_name, ctor, setup, upd)
    probe_rounds = (probe_upd,) if isinstance(probe_upd, str) else probe_upd
    for r in probe_rounds:
        exec(f"m.update({r})", ns_probe)
    probe_val = ns_probe["m"].compute()
    if not (isinstance(probe_val, (float, int)) or getattr(probe_val, "size", 0) == 1):
        pytest.skip("MinMaxMetric requires a scalar-compute base metric")
    ns_fn, _ = lifecycle._build(module_name, cls_name, ctor, setup, upd)
    rounds = (upd,) if isinstance(upd, str) else upd

    oo = MinMaxMetric(ns_oo["m"])
    fn = MinMaxMetric(ns_fn["m"])
    state = fn.functional_init()
    for _ in range(2):
        for r in rounds:
            nsx = dict(ns_oo)
            nsx["w"] = oo
            exec(f"w.update({r})", nsx)
            nsy = dict(ns_fn)
            nsy["w"], nsy["state"] = fn, state
            exec(f"state = w.functional_update(state, {r})", nsy)
            state = nsy["state"]
    res_fn = fn.functional_compute(state)
    res_oo = oo.compute()
    assert set(res_fn) == set(res_oo)
    for k in res_oo:
        _tree_allclose(res_fn[k], res_oo[k])
