"""Class-axis sharded metric state (ISSUE 16).

Covers the three layers of the feature:

- the layout + sparse routing kernel itself (``parallel/class_shard.py``):
  property tests that every (index, value) contribution lands exactly once
  across shards — no double-count, no drop — including boundary classes at
  shard edges, padded tails, and sentinel/quarantined rows that must ship
  but never land;
- the ``add_state(state_sharding=...)`` declaration surface: eligibility
  validation (cat/list/0-d raise), env + ctor policy resolution, trace-config
  cache-key split, spec/pickle round-trips;
- the adopters: MulticlassConfusionMatrix / MultilabelConfusionMatrix /
  stat-scores bit-exact vs the dense path, checkpoint round-trips through
  strict and elastic topology gates.

Runs on the 8-fake-device CPU mesh from conftest.py.
"""
import copy
import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo/tests")

from torchmetrics_tpu import Metric  # noqa: E402
from torchmetrics_tpu.classification import (  # noqa: E402
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.io import restore_state, save_state  # noqa: E402
from torchmetrics_tpu.io.checkpoint import load_manifest  # noqa: E402
from torchmetrics_tpu.parallel import class_shard as cs  # noqa: E402
from torchmetrics_tpu.utils.exceptions import TopologyMismatchError  # noqa: E402


# --------------------------------------------------------------- layout math
class TestLayoutMath:
    @pytest.mark.parametrize("C", [1, 7, 8, 16, 257, 1000])
    @pytest.mark.parametrize("S", [1, 2, 4, 8])
    def test_bounds_partition_the_class_axis(self, C, S):
        lay = cs.shard_layout(C, S)
        assert lay.shard_size == -(-C // S)
        assert lay.padded_classes == S * lay.shard_size >= C
        covered = []
        for s in range(S):
            start, stop = lay.bounds(s)
            covered.extend(range(start, stop))
        # every class owned exactly once, in order
        assert covered == list(range(C))

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            cs.shard_layout(0, 4)
        with pytest.raises(ValueError):
            cs.shard_layout(10, 0)
        with pytest.raises(ValueError):
            cs.shard_layout(10, 4).bounds(4)

    @pytest.mark.parametrize("C,S", [(257, 8), (8, 8), (5, 8), (64, 4)])
    def test_stack_gather_roundtrip(self, C, S):
        lay = cs.shard_layout(C, S)
        dense = jnp.arange(C * 3, dtype=jnp.float32).reshape(C, 3)
        stacked = cs.stack_dense(dense, lay)
        assert stacked.shape == (S, lay.shard_size, 3)
        np.testing.assert_array_equal(np.asarray(cs.gather_dense(stacked, lay)), np.asarray(dense))

    def test_padded_tail_carries_the_identity(self):
        lay = cs.shard_layout(5, 4)  # shard_size 2, padded 8: 3 pad rows
        stacked = cs.stack_dense(jnp.ones(5), lay, pad_value=cs.identity_pad_value("max", jnp.float32))
        flat = np.asarray(stacked).reshape(-1)
        assert np.all(flat[5:] == -np.inf)

    def test_shape_mismatch_raises_typed(self):
        lay = cs.shard_layout(10, 2)
        with pytest.raises(TopologyMismatchError):
            cs.gather_dense(jnp.zeros((3, 5)), lay)
        with pytest.raises(TopologyMismatchError):
            cs.stack_dense(jnp.zeros(11), lay)


# ------------------------------------------------- sparse routing properties
class TestRoutingKernel:
    """Every contribution lands exactly once; non-owned rows never land."""

    @pytest.mark.parametrize("C,S", [(257, 8), (8, 8), (64, 1), (16, 4)])
    def test_lands_exactly_once_random(self, C, S):
        rng = np.random.RandomState(C * 31 + S)
        lay = cs.shard_layout(C, S)
        idx = rng.randint(0, C, 5000)
        vals = rng.randint(1, 5, 5000)
        stacked = cs.route_scatter_add(
            jnp.zeros((S, lay.shard_size), jnp.int32), jnp.asarray(idx), jnp.asarray(vals), layout=lay
        )
        expected = np.bincount(idx, weights=vals, minlength=C).astype(np.int64)
        np.testing.assert_array_equal(np.asarray(cs.gather_dense(stacked, lay), dtype=np.int64), expected)

    def test_boundary_classes_at_shard_edges(self):
        lay = cs.shard_layout(257, 8)  # shard_size 33
        edges = []
        for s in range(8):
            start, stop = lay.bounds(s)
            edges.extend([start, max(start, stop - 1)])
        edges = [e for e in edges if e < 257]
        stacked = cs.route_scatter_add(
            jnp.zeros((8, lay.shard_size), jnp.int32),
            jnp.asarray(edges),
            jnp.ones(len(edges), jnp.int32),
            layout=lay,
        )
        dense = np.asarray(cs.gather_dense(stacked, lay))
        expected = np.bincount(np.asarray(edges), minlength=257)
        np.testing.assert_array_equal(dense, expected)
        assert dense.sum() == len(edges)  # nothing doubled, nothing dropped

    def test_sentinel_rows_ship_but_never_land(self):
        """Quarantined/ignored rows (sentinel -1, the lanes row-screen
        convention) and garbage labels past C are dropped on device — and a
        negative sentinel must NOT wrap into the last class row."""
        lay = cs.shard_layout(257, 8)
        junk = jnp.asarray([-1, -1, 257, 300, 10_000, -999])
        stacked = cs.route_scatter_add(
            jnp.zeros((8, lay.shard_size), jnp.int32), junk, jnp.ones(6, jnp.int32), layout=lay
        )
        assert int(np.asarray(stacked).sum()) == 0
        # padded tail untouched too
        tail = np.asarray(stacked).reshape(-1)[257:]
        assert np.all(tail == 0)

    def test_padded_tail_never_receives_contributions(self):
        lay = cs.shard_layout(5, 4)  # padded 8
        stacked = cs.route_scatter_add(
            jnp.zeros((4, 2), jnp.int32),
            jnp.asarray([0, 4, 4, 5, 6, 7, 8]),  # 5..8 invalid (>= C)
            jnp.ones(7, jnp.int32),
            layout=lay,
        )
        flat = np.asarray(stacked).reshape(-1)
        np.testing.assert_array_equal(flat, [1, 0, 0, 0, 2, 0, 0, 0])

    def test_inner_idx_cells(self):
        rng = np.random.RandomState(7)
        lay = cs.shard_layout(13, 4)
        rows = rng.randint(-1, 13, 800)  # includes sentinel -1
        cols = rng.randint(0, 13, 800)
        stacked = cs.route_scatter_add(
            jnp.zeros((4, lay.shard_size, 13), jnp.int32),
            jnp.asarray(rows),
            jnp.ones(800, jnp.int32),
            inner_idx=jnp.asarray(cols),
            layout=lay,
        )
        dense = np.asarray(cs.gather_dense(stacked, lay))
        expected = np.zeros((13, 13), np.int64)
        for r, c in zip(rows, cols):
            if 0 <= r < 13:
                expected[r, c] += 1
        np.testing.assert_array_equal(dense.astype(np.int64), expected)

    def test_add_dense_matches_dense_accumulation(self):
        rng = np.random.RandomState(3)
        lay = cs.shard_layout(257, 8)
        stacked = jnp.zeros((8, lay.shard_size), jnp.int32)
        acc = np.zeros(257, np.int64)
        for _ in range(3):
            contrib = rng.randint(0, 9, 257)
            stacked = cs.add_dense(stacked, jnp.asarray(contrib), lay)
            acc += contrib
        np.testing.assert_array_equal(np.asarray(cs.gather_dense(stacked, lay), dtype=np.int64), acc)

    def test_route_without_inner_requires_rank2(self):
        lay = cs.shard_layout(8, 2)
        with pytest.raises(TopologyMismatchError):
            cs.route_scatter_add(
                jnp.zeros((2, 4, 3)), jnp.asarray([1]), jnp.asarray([1.0]), layout=lay
            )


# ------------------------------------------------ declaration surface (sat 1)
class _Hist(Metric):
    full_state_update = False

    def __init__(self, n=10, sharding=None, **kw):
        self._n, self._sharding = n, sharding
        super().__init__(**kw)
        self.add_state("hist", jnp.zeros(n, jnp.int32), dist_reduce_fx="sum", state_sharding=sharding)

    def update(self, idx):
        lay = self._class_layout("hist")
        ones = jnp.ones(jnp.asarray(idx).shape, jnp.int32)
        if lay is not None:
            self.hist = cs.route_scatter_add(self.hist, idx, ones, layout=lay)
        else:
            self.hist = self.hist.at[idx].add(ones, mode="drop")

    def compute(self):
        lay = self._class_layout("hist")
        return cs.gather_dense(self.hist, lay) if lay is not None else self.hist


class TestAddStateValidation:
    def test_class_axis_on_list_state_raises(self):
        class Bad(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("x", [], dist_reduce_fx="cat", state_sharding="class_axis")

        with pytest.raises(ValueError, match="class_axis"):
            Bad()

    def test_class_axis_on_scalar_raises(self):
        class Bad(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum", state_sharding="class_axis")

        with pytest.raises(ValueError, match="rank-0"):
            Bad()

    @pytest.mark.parametrize("fx", ["cat", None])
    def test_class_axis_on_non_shardable_reduction_raises(self, fx):
        class Bad(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("x", jnp.zeros(4), dist_reduce_fx=fx, state_sharding="class_axis")

        with pytest.raises(ValueError, match="dist_reduce_fx"):
            Bad()

    def test_bogus_sharding_value_raises(self):
        class Bad(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("x", jnp.zeros(4), dist_reduce_fx="sum", state_sharding="diagonal")

        with pytest.raises(ValueError, match="diagonal"):
            Bad()

    def test_dist_reduce_fx_error_names_the_offender(self):
        class Bad(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("x", jnp.zeros(4), dist_reduce_fx="bogus")

        with pytest.raises(ValueError, match="'bogus'"):
            Bad()

    def test_metric_ctor_knobs_validated(self):
        with pytest.raises(ValueError, match="state_sharding"):
            _Hist(state_sharding="diagonal")
        with pytest.raises(ValueError, match="class_shards"):
            _Hist(class_shards=0)

    def test_env_default_applies_to_eligible_states_only(self, monkeypatch):
        monkeypatch.setenv(cs.STATE_SHARDING_ENV, "class_axis")

        class Mixed(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("hist", jnp.zeros(16, jnp.int32), dist_reduce_fx="sum")
                self.add_state("count", jnp.asarray(0), dist_reduce_fx="sum")  # 0-d: ineligible
                self.add_state("vals", [], dist_reduce_fx="cat")  # list: ineligible

            def update(self):
                pass

            def compute(self):
                return self.count

        m = Mixed(class_shards=4)
        assert m._state_shardings["hist"] == "class_axis"
        assert m._state["hist"].shape == (4, 4)
        assert m._state_shardings["count"] == "replicated"
        assert m._state_shardings["vals"] == "replicated"

    def test_env_bogus_value_raises(self, monkeypatch):
        monkeypatch.setenv(cs.STATE_SHARDING_ENV, "sideways")
        with pytest.raises(ValueError, match="sideways"):
            cs.default_state_sharding()

    def test_explicit_replicated_pins_against_policy(self):
        class Pinned(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("h", jnp.zeros(8, jnp.int32), dist_reduce_fx="sum", state_sharding="replicated")

            def update(self):
                pass

            def compute(self):
                return self.h

        m = Pinned(state_sharding="class_axis", class_shards=4)
        assert m._state_shardings["h"] == "replicated"
        assert m._state["h"].shape == (8,)


class TestDeclarationPlumbing:
    def test_trace_config_splits_sharded_from_dense(self):
        dense = _Hist(64)
        sharded = _Hist(64, sharding="class_axis", class_shards=8)
        assert dense._trace_config() != sharded._trace_config()
        assert any("state_sharding" in c for c in sharded._trace_config())

    def test_state_spec_carries_layout(self):
        m = _Hist(10, sharding="class_axis", class_shards=4)
        fs = m.state_spec()["fields"]["hist"]
        assert fs["state_sharding"] == "class_axis"
        assert fs["num_classes"] == 10 and fs["class_shards"] == 4
        assert fs["shape"] == (4, 3)
        # replicated fields keep their pre-sharding spec exactly
        assert "state_sharding" not in _Hist(10).state_spec()["fields"]["hist"]

    def test_pickle_and_deepcopy_roundtrip(self):
        import pickle

        m = _Hist(10, sharding="class_axis", class_shards=4, executor=False)
        m.update(jnp.asarray([1, 9, 9]))
        for clone in (pickle.loads(pickle.dumps(m)), copy.deepcopy(m)):
            assert clone._class_layout("hist") == cs.ClassShardLayout(10, 4)
            clone.update(jnp.asarray([0]))
            np.testing.assert_array_equal(
                np.asarray(clone.compute()), [1, 1, 0, 0, 0, 0, 0, 0, 0, 2]
            )

    def test_reset_restores_stacked_default(self):
        m = _Hist(10, sharding="class_axis", class_shards=4, executor=False)
        m.update(jnp.asarray([3]))
        m.reset()
        assert m._state["hist"].shape == (4, 3)
        assert int(np.asarray(m._state["hist"]).sum()) == 0

    def test_load_state_adopts_dense_and_foreign_layouts(self):
        src = _Hist(10, sharding="class_axis", class_shards=8, executor=False)
        src.update(jnp.asarray([0, 9, 9, 5]))
        # dense snapshot installs into the stacked layout
        dense_target = _Hist(10, sharding="class_axis", class_shards=4, executor=False)
        dense_target.load_state({"hist": np.asarray(src.compute())})
        np.testing.assert_array_equal(np.asarray(dense_target.compute()), np.asarray(src.compute()))
        # 8-shard stack re-splits onto 2 shards exactly
        two = _Hist(10, sharding="class_axis", class_shards=2, executor=False)
        two.load_state(src.state())
        np.testing.assert_array_equal(np.asarray(two.compute()), np.asarray(src.compute()))
        # and a sharded save restores into a REPLICATED twin via validate's
        # shape check only when the layout matches dense — the dense twin
        # reads the dense export (state() of a replicated metric) unchanged
        rep = _Hist(10, executor=False)
        rep.load_state({"hist": np.asarray(src.compute())})
        np.testing.assert_array_equal(np.asarray(rep.compute()), np.asarray(src.compute()))


# ----------------------------------------------------------------- adopters
class TestAdopterParity:
    def test_multiclass_confusion_matrix_bit_exact(self):
        rng = np.random.RandomState(0)
        C = 257  # odd: exercises the padded tail
        for ignore in (None, 3):
            dense = MulticlassConfusionMatrix(num_classes=C, ignore_index=ignore, executor=False)
            sharded = MulticlassConfusionMatrix(
                num_classes=C, ignore_index=ignore, state_sharding="class_axis",
                class_shards=8, executor=False,
            )
            for _ in range(3):
                p = jnp.asarray(rng.randint(0, C, 400))
                t = jnp.asarray(rng.randint(0, C, 400))
                if ignore is not None:
                    t = jnp.where(jnp.asarray(rng.rand(400) < 0.1), ignore, t)
                dense.update(p, t)
                sharded.update(p, t)
            assert sharded._state["confmat"].shape == (8, 33, C)
            np.testing.assert_array_equal(np.asarray(dense.compute()), np.asarray(sharded.compute()))

    def test_multiclass_normalize_variants(self):
        rng = np.random.RandomState(5)
        p = jnp.asarray(rng.randint(0, 9, 300))
        t = jnp.asarray(rng.randint(0, 9, 300))
        for norm in (None, "true", "pred", "all"):
            dense = MulticlassConfusionMatrix(num_classes=9, normalize=norm, executor=False)
            sharded = MulticlassConfusionMatrix(
                num_classes=9, normalize=norm, state_sharding="class_axis", class_shards=4, executor=False
            )
            dense.update(p, t)
            sharded.update(p, t)
            np.testing.assert_allclose(np.asarray(dense.compute()), np.asarray(sharded.compute()), rtol=1e-6)

    def test_multilabel_confusion_matrix_bit_exact(self):
        rng = np.random.RandomState(1)
        L = 13
        dense = MultilabelConfusionMatrix(num_labels=L, ignore_index=-1, executor=False)
        sharded = MultilabelConfusionMatrix(
            num_labels=L, ignore_index=-1, state_sharding="class_axis", class_shards=8, executor=False
        )
        for _ in range(3):
            p = jnp.asarray(rng.rand(40, L))
            t = jnp.where(jnp.asarray(rng.rand(40, L) < 0.1), -1, jnp.asarray(rng.randint(0, 2, (40, L))))
            dense.update(p, t)
            sharded.update(p, t)
        np.testing.assert_array_equal(np.asarray(dense.compute()), np.asarray(sharded.compute()))

    def test_stat_scores_family_bit_exact(self):
        rng = np.random.RandomState(2)
        C = 37
        dense = MulticlassAccuracy(num_classes=C, average="macro", executor=False)
        sharded = MulticlassAccuracy(
            num_classes=C, average="macro", state_sharding="class_axis", class_shards=8, executor=False
        )
        for _ in range(3):
            p = jnp.asarray(rng.randint(0, C, 200))
            t = jnp.asarray(rng.randint(0, C, 200))
            dense.update(p, t)
            sharded.update(p, t)
        assert sharded._state["tp"].shape == (8, 5)
        np.testing.assert_allclose(np.asarray(dense.compute()), np.asarray(sharded.compute()), rtol=1e-6)

    def test_executor_donation_path_parity(self):
        rng = np.random.RandomState(4)
        dense = MulticlassConfusionMatrix(num_classes=64, executor=True)
        sharded = MulticlassConfusionMatrix(
            num_classes=64, state_sharding="class_axis", class_shards=8, executor=True
        )
        for _ in range(4):
            p = jnp.asarray(rng.randint(0, 64, 100))
            t = jnp.asarray(rng.randint(0, 64, 100))
            dense.update(p, t)
            sharded.update(p, t)
        np.testing.assert_array_equal(np.asarray(dense.compute()), np.asarray(sharded.compute()))

    def test_forward_merges_batch_state(self):
        rng = np.random.RandomState(6)
        m = MulticlassConfusionMatrix(
            num_classes=17, state_sharding="class_axis", class_shards=4, executor=False
        )
        p = jnp.asarray(rng.randint(0, 17, 50))
        t = jnp.asarray(rng.randint(0, 17, 50))
        batch_val = m(p, t)
        assert np.asarray(batch_val).shape == (17, 17)
        np.testing.assert_array_equal(np.asarray(batch_val), np.asarray(m.compute()))


# ------------------------------------------------ checkpoint topology (sat 2)
class TestCheckpointTopology:
    def _fill(self, m, seed=0, C=41):
        rng = np.random.RandomState(seed)
        for _ in range(2):
            m.update(jnp.asarray(rng.randint(0, C, 100)), jnp.asarray(rng.randint(0, C, 100)))
        return m

    def test_manifest_topology_binds_class_shards(self, tmp_path):
        m = self._fill(MulticlassConfusionMatrix(
            num_classes=41, state_sharding="class_axis", class_shards=8, executor=False
        ))
        path = str(tmp_path / "cs.ckpt")
        save_state(m, path)
        assert load_manifest(path)["topology"]["state_sharding"] == 8
        dense = self._fill(MulticlassConfusionMatrix(num_classes=41, executor=False))
        dense_path = str(tmp_path / "dense.ckpt")
        save_state(dense, dense_path)
        assert load_manifest(dense_path)["topology"]["state_sharding"] is None

    def test_strict_same_layout_roundtrips_bit_exact(self, tmp_path):
        m = self._fill(MulticlassConfusionMatrix(
            num_classes=41, state_sharding="class_axis", class_shards=8, executor=False
        ))
        path = str(tmp_path / "cs.ckpt")
        save_state(m, path)
        m2 = MulticlassConfusionMatrix(
            num_classes=41, state_sharding="class_axis", class_shards=8, executor=False
        )
        info = restore_state(path, m2, topology="strict")
        assert info["topology_action"] == "match"
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(m2.compute()))

    def test_strict_cross_layout_raises_elastic_resplits(self, tmp_path):
        m = self._fill(MulticlassConfusionMatrix(
            num_classes=41, state_sharding="class_axis", class_shards=8, executor=False
        ))
        path = str(tmp_path / "cs.ckpt")
        save_state(m, path)
        for target_shards in (1, 2, 4):
            strict = MulticlassConfusionMatrix(
                num_classes=41, state_sharding="class_axis", class_shards=target_shards, executor=False
            )
            with pytest.raises(TopologyMismatchError):
                restore_state(path, strict, topology="strict")
            elastic = MulticlassConfusionMatrix(
                num_classes=41, state_sharding="class_axis", class_shards=target_shards, executor=False
            )
            info = restore_state(path, elastic, topology="elastic")
            assert info["topology_action"] == "reshard"
            np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(elastic.compute()))


# ------------------------------------------------- cell-granular recovery mirror
class TestRecoveryMirror:
    """ISSUE 17 satellite: the executor's recovery snapshot for class-sharded
    metrics is CELLS-sized (the batch's touched ``(target, pred)`` cells), not
    state-sized — bench config 10 runs its 50k-class rows with recovery ON
    because of this. The mirror must stay bit-exact with the full copy it
    replaces, and fall back to a full rebuild whenever the one-snapshot-per-
    commit chain is provably broken."""

    C = 41

    def _batch(self, seed, n=64):
        rng = np.random.RandomState(seed)
        return (
            jnp.asarray(rng.randint(0, self.C, n).astype(np.int64)),
            jnp.asarray(rng.randint(0, self.C, n).astype(np.int64)),
        )

    def test_touched_cells_cover_exactly_the_batch(self):
        m = MulticlassConfusionMatrix(
            num_classes=self.C, state_sharding="class_axis", class_shards=8, executor=False
        )
        preds, target = self._batch(0)
        state = {k: jnp.asarray(v) for k, v in m.metric_state.items()}
        cells = m._touched_class_cells(state, (preds, target))
        assert set(cells) == {"confmat"}
        want = np.unique(np.asarray(target) * self.C + np.asarray(preds))
        np.testing.assert_array_equal(np.sort(np.asarray(cells["confmat"])), want)

    def test_touched_cells_honour_ignore_index(self):
        m = MulticlassConfusionMatrix(
            num_classes=self.C,
            state_sharding="class_axis",
            class_shards=8,
            ignore_index=3,
            executor=False,
        )
        preds = jnp.asarray(np.array([0, 1, 2], np.int64))
        target = jnp.asarray(np.array([3, 3, 5], np.int64))
        state = {k: jnp.asarray(v) for k, v in m.metric_state.items()}
        cells = m._touched_class_cells(state, (preds, target))
        np.testing.assert_array_equal(np.asarray(cells["confmat"]), [5 * self.C + 2])

    def test_dense_metric_offers_no_partial_snapshot(self):
        m = MulticlassConfusionMatrix(num_classes=self.C, executor=False)
        preds, target = self._batch(1)
        state = {k: jnp.asarray(v) for k, v in m.metric_state.items()}
        assert m._recovery_snapshot(state, (preds, target)) is None

    def test_mirror_incremental_fold_is_bit_exact(self):
        """Direct protocol drive: snapshot_i sees the pre-dispatch state and
        round i's cells; the incremental fold of round i-1's cells must land
        on exactly the state a full copy would have taken."""
        mirror = cs.ClassShardMirror()
        state = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        r1 = mirror.snapshot({"x": jnp.asarray(state)}, {"x": np.array([2, 5])}, 1)
        assert mirror.stats == {"rebuilds": 1, "incremental": 0}
        np.testing.assert_array_equal(r1.materialize()["x"], state)

        state2 = state.copy()
        state2.reshape(-1)[[2, 5]] += 100.0  # commit 1 touched its declared cells
        r2 = mirror.snapshot({"x": jnp.asarray(state2)}, {"x": np.array([7, 7, -3, 999])}, 2)
        assert mirror.stats == {"rebuilds": 1, "incremental": 1}
        np.testing.assert_array_equal(r2.materialize()["x"], state2)

        state3 = state2.copy()
        state3.reshape(-1)[[7]] += 1.0
        r3 = mirror.snapshot({"x": jnp.asarray(state3)}, {"x": np.zeros((0,), np.int64)}, 3)
        assert mirror.stats == {"rebuilds": 1, "incremental": 2}
        np.testing.assert_array_equal(r3.materialize()["x"], state3)

    def test_mirror_chain_breaks_force_full_rebuild(self):
        mirror = cs.ClassShardMirror()
        state = np.zeros((2, 3, 4), np.float32)
        mirror.snapshot({"x": jnp.asarray(state)}, {"x": np.array([0])}, 1)
        # a commit bypassed the hook: counter jumps 1 -> 3
        mirror.snapshot({"x": jnp.asarray(state)}, {"x": np.array([1])}, 3)
        assert mirror.stats["rebuilds"] == 2
        # layout change: shape mismatch
        mirror.snapshot({"x": jnp.asarray(np.zeros((4, 3, 2), np.float32))}, {"x": np.array([0])}, 4)
        assert mirror.stats["rebuilds"] == 3
        # restore-after-failure (as_state) deliberately breaks the chain
        rec = mirror.snapshot({"x": jnp.asarray(np.zeros((4, 3, 2), np.float32))}, {"x": np.array([0])}, 5)
        assert mirror.stats["incremental"] == 1
        rec.as_state()
        mirror.snapshot({"x": jnp.asarray(np.zeros((4, 3, 2), np.float32))}, {"x": np.array([0])}, 6)
        assert mirror.stats["rebuilds"] == 4

    def test_executor_donating_dispatch_rides_the_mirror(self):
        """End-to-end through the real executor: warm donated dispatches take
        cells-sized snapshots (one rebuild, then incrementals), and the
        Autosaver-facing ``latest_recovery_snapshot`` stays exactly one
        committed update behind with the right stacked values."""
        from torchmetrics_tpu.ops.executor import latest_recovery_snapshot

        m = MulticlassConfusionMatrix(
            num_classes=self.C, state_sharding="class_axis", class_shards=8, validate_args=False
        )
        batches = [self._batch(s) for s in range(6)]
        for preds, target in batches:
            m.update(preds, target)
        assert m.executor_status["stats"]["donated_calls"] >= 2
        mirror = m.__dict__.get("_class_mirror")
        assert mirror is not None
        assert mirror.stats["rebuilds"] == 1 and mirror.stats["incremental"] >= 1

        snap = latest_recovery_snapshot(m)
        assert snap is not None
        count, export = snap
        assert count == m.update_count - 1
        twin = MulticlassConfusionMatrix(
            num_classes=self.C, state_sharding="class_axis", class_shards=8, executor=False
        )
        for preds, target in batches[:count]:
            twin.update(preds, target)
        np.testing.assert_array_equal(
            np.asarray(export["confmat"]), np.asarray(twin.metric_state["confmat"])
        )
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(self._dense(batches)))

    def _dense(self, batches):
        ref = MulticlassConfusionMatrix(num_classes=self.C, executor=False)
        for preds, target in batches:
            ref.update(preds, target)
        return np.asarray(ref.compute())
