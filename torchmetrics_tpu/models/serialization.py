"""Flat npz (de)serialization for model parameter trees.

The real-weights bundle produced by tools/fetch_model_weights.py stores each
converted flax parameter tree as one ``.npz`` with ``/``-joined dict paths as
keys — loadable without orbax and stable across jax versions.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np


def flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    """Nested dict/list parameter tree -> flat ``{"a/b/c": array}`` mapping.

    List nodes (e.g. the LPIPS ``lins`` head list) flatten under ``#{i}``
    segment names so :func:`unflatten_tree` can rebuild them as lists."""
    flat: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            flat.update(flatten_tree(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            flat.update(flatten_tree(v, f"{prefix}#{i}/"))
    else:
        flat[prefix.rstrip("/")] = np.asarray(tree)
    return flat


def unflatten_tree(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_tree`."""
    tree: Dict[str, Any] = {}
    for key, value in flat.items():
        node = tree
        parts = key.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value

    def _listify(node: Any) -> Any:
        if not isinstance(node, dict):
            return node
        if node and all(k.startswith("#") for k in node):
            return [_listify(node[f"#{i}"]) for i in range(len(node))]
        return {k: _listify(v) for k, v in node.items()}

    return _listify(tree)


def load_npz_tree(path: str) -> Dict[str, Any]:
    """Load a ``flatten_tree`` npz bundle back into a parameter tree."""
    with np.load(path) as data:
        return unflatten_tree({k: data[k] for k in data.files})
