"""Flax model ports backing the model-based metrics.

These replace the third-party native/torch networks the reference leans on
(SURVEY §2.16): torchvision alex/vgg/squeeze feature stacks for LPIPS,
torch-fidelity's InceptionV3 for FID/KID/IS/MiFID. Weights are not bundled —
every consumer metric accepts loadable params or a callable escape hatch.
"""
from torchmetrics_tpu.models import inception, lpips  # noqa: F401
