"""Flax backbones + lin heads for LPIPS.

Architecture-faithful ports of the three torchvision feature stacks the
reference LPIPS uses (reference functional/image/lpips.py:66-203: SqueezeNet
slices, Alexnet slices, Vgg16 slices), exposed NCHW like the reference, plus a
``lpips_network`` factory producing the ``net(img1, img2) -> (N,)`` scoring
callable the LPIPS metric consumes. Weights are loadable either as a flax
param tree or converted from a reference ``_LPIPS.state_dict()`` via
:func:`params_from_torch_state_dict` (OIHW → HWIO transposition + slice-index
remapping).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import Array

LPIPS_CHANNELS: Dict[str, Tuple[int, ...]] = {
    "alex": (64, 192, 384, 256, 256),
    "vgg": (64, 128, 256, 512, 512),
    "squeeze": (64, 128, 256, 384, 384, 512, 512),
}


def _max_pool(x: Array, window: int, stride: int, ceil_mode: bool = False) -> Array:
    """Torch-semantics max pool on NHWC (VALID, optional ceil_mode padding)."""
    h, w = x.shape[1], x.shape[2]
    if ceil_mode:
        out_h = -(-(h - window) // stride) + 1
        out_w = -(-(w - window) // stride) + 1
        pad_h = max(0, (out_h - 1) * stride + window - h)
        pad_w = max(0, (out_w - 1) * stride + window - w)
        padding = ((0, 0), (0, pad_h), (0, pad_w), (0, 0))
    else:
        padding = ((0, 0), (0, 0), (0, 0), (0, 0))
    return jax.lax.reduce_window(
        x,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=padding,
    )


class AlexNetFeatures(nn.Module):
    """torchvision ``alexnet().features`` sliced at each ReLU (lpips.py:105-152)."""

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        feats = []
        x = nn.relu(nn.Conv(64, (11, 11), strides=(4, 4), padding=((2, 2), (2, 2)), name="conv1")(x))
        feats.append(x)
        x = _max_pool(x, 3, 2)
        x = nn.relu(nn.Conv(192, (5, 5), padding=((2, 2), (2, 2)), name="conv2")(x))
        feats.append(x)
        x = _max_pool(x, 3, 2)
        x = nn.relu(nn.Conv(384, (3, 3), padding=((1, 1), (1, 1)), name="conv3")(x))
        feats.append(x)
        x = nn.relu(nn.Conv(256, (3, 3), padding=((1, 1), (1, 1)), name="conv4")(x))
        feats.append(x)
        x = nn.relu(nn.Conv(256, (3, 3), padding=((1, 1), (1, 1)), name="conv5")(x))
        feats.append(x)
        return feats


class VGG16Features(nn.Module):
    """torchvision ``vgg16().features`` sliced at relu{1_2,2_2,3_3,4_3,5_3} (lpips.py:155-203)."""

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        feats = []
        cfg = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        idx = 0
        for block, (ch, n_convs) in enumerate(cfg):
            if block > 0:
                x = _max_pool(x, 2, 2)
            for _ in range(n_convs):
                idx += 1
                x = nn.relu(nn.Conv(ch, (3, 3), padding=((1, 1), (1, 1)), name=f"conv{idx}")(x))
            feats.append(x)
        return feats


class Fire(nn.Module):
    """SqueezeNet fire module: 1x1 squeeze → parallel 1x1/3x3 expand, concat."""

    squeeze: int
    expand: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        s = nn.relu(nn.Conv(self.squeeze, (1, 1), name="squeeze")(x))
        e1 = nn.relu(nn.Conv(self.expand, (1, 1), name="expand1x1")(s))
        e3 = nn.relu(nn.Conv(self.expand, (3, 3), padding=((1, 1), (1, 1)), name="expand3x3")(s))
        return jnp.concatenate([e1, e3], axis=-1)


class SqueezeNetFeatures(nn.Module):
    """torchvision ``squeezenet1_1().features`` in 7 LPIPS slices (lpips.py:66-103)."""

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        feats = []
        x = nn.relu(nn.Conv(64, (3, 3), strides=(2, 2), padding="VALID", name="conv1")(x))
        feats.append(x)
        x = _max_pool(x, 3, 2, ceil_mode=True)
        x = Fire(16, 64, name="fire3")(x)
        x = Fire(16, 64, name="fire4")(x)
        feats.append(x)
        x = _max_pool(x, 3, 2, ceil_mode=True)
        x = Fire(32, 128, name="fire6")(x)
        x = Fire(32, 128, name="fire7")(x)
        feats.append(x)
        x = _max_pool(x, 3, 2, ceil_mode=True)
        x = Fire(48, 192, name="fire9")(x)
        feats.append(x)
        x = Fire(48, 192, name="fire10")(x)
        feats.append(x)
        x = Fire(64, 256, name="fire11")(x)
        feats.append(x)
        x = Fire(64, 256, name="fire12")(x)
        feats.append(x)
        return feats


_BACKBONES = {"alex": AlexNetFeatures, "vgg": VGG16Features, "squeeze": SqueezeNetFeatures}


def init_lpips_params(net_type: str = "alex", key: Optional[Array] = None, image_size: int = 64) -> Dict[str, Any]:
    """Random-init param tree {"backbone": flax params, "lins": [(C_k,) arrays]}.

    Mirrors the reference's ``pretrained=False`` mode (random backbone, random
    lin heads) — deterministic given ``key``; load real weights for meaningful
    scores.
    """
    if net_type not in _BACKBONES:
        raise ValueError(f"Argument `net_type` must be one of {list(_BACKBONES)}, got {net_type}")
    key = key if key is not None else jax.random.PRNGKey(0)
    bkey, *lkeys = jax.random.split(key, 1 + len(LPIPS_CHANNELS[net_type]))
    module = _BACKBONES[net_type]()
    dummy = jnp.zeros((1, image_size, image_size, 3), dtype=jnp.float32)
    backbone = module.init(bkey, dummy)["params"]
    lins = [
        jax.random.uniform(k, (c,), dtype=jnp.float32)
        for k, c in zip(lkeys, LPIPS_CHANNELS[net_type])
    ]
    return {"backbone": backbone, "lins": lins}


def lpips_network(
    net_type: str = "alex",
    params: Optional[Dict[str, Any]] = None,
) -> Callable[[Array, Array], Array]:
    """Build the ``net(img1, img2) -> (N,)`` scoring callable for LPIPS.

    Inputs are NCHW in [-1, 1] (the metric handles the ``normalize`` flag).
    ``params`` as from :func:`init_lpips_params` /
    :func:`params_from_torch_state_dict`; random-init if omitted.
    """
    from torchmetrics_tpu.functional.image.lpips import _lpips_score

    if net_type not in _BACKBONES:
        raise ValueError(f"Argument `net_type` must be one of {list(_BACKBONES)}, got {net_type}")
    if params is None:
        params = init_lpips_params(net_type)
    module = _BACKBONES[net_type]()
    if "backbone" not in params or "lins" not in params:
        raise KeyError(
            "LPIPS params must contain both 'backbone' and 'lins' keys"
            f" (got {sorted(params)}); build them via init_lpips_params or"
            " params_from_torch_state_dict."
        )
    backbone_params = params["backbone"]
    lins = params["lins"]

    def feature_stack(img_nchw: Array) -> Sequence[Array]:
        feats = module.apply({"params": backbone_params}, jnp.transpose(img_nchw, (0, 2, 3, 1)))
        return [jnp.transpose(f, (0, 3, 1, 2)) for f in feats]

    def net(img1: Array, img2: Array) -> Array:
        return _lpips_score(img1, img2, feature_stack, lin_weights=lins, normalize=False)

    return net


# torchvision features-sequence index of each conv, per backbone slice layout
# (reference lpips.py:74-76,116-126,166-180) — used to translate state-dict keys.
_TORCH_CONV_INDEX = {
    "alex": {"conv1": ("slice1", 0), "conv2": ("slice2", 3), "conv3": ("slice3", 6),
             "conv4": ("slice4", 8), "conv5": ("slice5", 10)},
    "vgg": {"conv1": ("slice1", 0), "conv2": ("slice1", 2), "conv3": ("slice2", 5),
            "conv4": ("slice2", 7), "conv5": ("slice3", 10), "conv6": ("slice3", 12),
            "conv7": ("slice3", 14), "conv8": ("slice4", 17), "conv9": ("slice4", 19),
            "conv10": ("slice4", 21), "conv11": ("slice5", 24), "conv12": ("slice5", 26),
            "conv13": ("slice5", 28)},
}
_SQUEEZE_FIRES = {"fire3": 3, "fire4": 4, "fire6": 6, "fire7": 7, "fire9": 9,
                  "fire10": 10, "fire11": 11, "fire12": 12}
_SQUEEZE_SLICE_OF = {0: "slices.0", 3: "slices.1", 4: "slices.1", 6: "slices.2", 7: "slices.2",
                     9: "slices.3", 10: "slices.4", 11: "slices.5", 12: "slices.6"}


def _oihw_to_hwio(w) -> Array:
    return jnp.transpose(jnp.asarray(w, dtype=jnp.float32), (2, 3, 1, 0))


def params_from_torch_state_dict(state_dict: Dict[str, Any], net_type: str = "alex") -> Dict[str, Any]:
    """Convert a reference ``_LPIPS.state_dict()`` (as numpy arrays) to our tree.

    Key layout of the source (reference lpips.py:260-331): backbone convs under
    ``net.slice{K}.{i}.weight/bias`` (``net.slices.{K}.{i}.*`` for squeeze),
    lin heads under ``lin{k}.model.1.weight`` with shape (1, C, 1, 1).
    """
    if net_type not in _BACKBONES:
        raise ValueError(f"Argument `net_type` must be one of {list(_BACKBONES)}, got {net_type}")
    backbone: Dict[str, Any] = {}
    if net_type in ("alex", "vgg"):
        for ours, (slc, idx) in _TORCH_CONV_INDEX[net_type].items():
            backbone[ours] = {
                "kernel": _oihw_to_hwio(state_dict[f"net.{slc}.{idx}.weight"]),
                "bias": jnp.asarray(state_dict[f"net.{slc}.{idx}.bias"], dtype=jnp.float32),
            }
    else:
        conv_slice = _SQUEEZE_SLICE_OF[0]
        backbone["conv1"] = {
            "kernel": _oihw_to_hwio(state_dict[f"net.{conv_slice}.0.weight"]),
            "bias": jnp.asarray(state_dict[f"net.{conv_slice}.0.bias"], dtype=jnp.float32),
        }
        for ours, idx in _SQUEEZE_FIRES.items():
            slc = _SQUEEZE_SLICE_OF[idx]
            fire: Dict[str, Any] = {}
            for part in ("squeeze", "expand1x1", "expand3x3"):
                fire[part] = {
                    "kernel": _oihw_to_hwio(state_dict[f"net.{slc}.{idx}.{part}.weight"]),
                    "bias": jnp.asarray(state_dict[f"net.{slc}.{idx}.{part}.bias"], dtype=jnp.float32),
                }
            backbone[ours] = fire
    n_lins = len(LPIPS_CHANNELS[net_type])
    lins = [
        jnp.asarray(state_dict[f"lin{k}.model.1.weight"], dtype=jnp.float32).reshape(-1)
        for k in range(n_lins)
    ]
    return {"backbone": backbone, "lins": lins}
