"""Flax InceptionV3 feature extractor for FID / KID / IS / MiFID.

Architecture-faithful port of torch-fidelity's FeatureExtractorInceptionV3
(the TF-1.x-compatible InceptionV3 the reference auto-loads, reference
image/fid.py:30-157), including its quirks:

- TF-1.x "legacy" bilinear resize to 299x299 (src = dst * in/out, NO
  half-pixel offset — torch-fidelity's interpolate_bilinear_2d_like_tensorflow1x)
- uint8 [0, 255] input scaled to [-1, 1]
- BasicConv2d = bias-free conv + BatchNorm(eps=1e-3) + relu
- FID-variant pooling quirks: count_exclude-pad average pools in the A/C/E1
  blocks, and a MAX pool in the final E2 block's pool branch
- feature taps at 64 (first pool), 192 (second pool), 768 (Mixed_6e) and
  2048 (global average pool) — the reference's `feature` integer choices

Pretrained weights are not bundled (zero-egress environment):
:func:`params_from_torch_fidelity_state_dict` converts the torch-fidelity
checkpoint offline into the params tree :func:`inception_feature_extractor`
takes; random init gives architecture-correct shapes for testing.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

VALID_FEATURE_DIMS = (64, 192, 768, 2048)
# string taps: the 1008-class TF-inception classifier head (torch-fidelity's
# 'logits_unbiased' = pre-bias fc output, what InceptionScore consumes)
VALID_FEATURE_KEYS = VALID_FEATURE_DIMS + ("logits", "logits_unbiased")
NUM_LOGITS = 1008


def _tf1_resize_matrix(in_size: int, out_size: int) -> np.ndarray:
    """Row matrix for TF-1.x legacy bilinear resize (align_corners=False, no
    half-pixel offset): src = dst * (in/out)."""
    scale = in_size / out_size
    mat = np.zeros((out_size, in_size), dtype=np.float32)
    for i in range(out_size):
        src = i * scale
        lo = int(math.floor(src))
        hi = min(lo + 1, in_size - 1)
        frac = src - lo
        mat[i, lo] += 1.0 - frac
        mat[i, hi] += frac
    return mat


def tf1_bilinear_resize(x: Array, size: int = 299) -> Array:
    """Resize NCHW images with TF-1.x legacy bilinear semantics."""
    h, w = x.shape[2], x.shape[3]
    if h == size and w == size:
        return x
    wh = jnp.asarray(_tf1_resize_matrix(h, size))
    ww = jnp.asarray(_tf1_resize_matrix(w, size))
    return jnp.einsum("oh,nchw,pw->ncop", wh, x, ww)


def _avg_pool_nopad(x: Array, window: int = 3, stride: int = 1) -> Array:
    """3x3/1 average pool with SAME extent but count_include_pad=False."""
    ones = jnp.ones(x.shape[1:3], dtype=x.dtype)[None, :, :, None]
    pad = ((0, 0), (window // 2, window // 2), (window // 2, window // 2), (0, 0))
    sums = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, window, window, 1), (1, stride, stride, 1), pad)
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, (1, window, window, 1), (1, stride, stride, 1), pad)
    return sums / counts


def _max_pool(x: Array, window: int, stride: int, same: bool = False) -> Array:
    pad = (
        ((0, 0), (window // 2, window // 2), (window // 2, window // 2), (0, 0))
        if same
        else ((0, 0), (0, 0), (0, 0), (0, 0))
    )
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, window, window, 1), (1, stride, stride, 1), pad)


class BasicConv2d(nn.Module):
    """Bias-free conv + BN(eps=1e-3, affine) + relu, inference mode."""

    features: int
    kernel: Sequence[int]
    strides: Sequence[int] = (1, 1)
    padding: Any = ((0, 0), (0, 0))

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = nn.Conv(self.features, tuple(self.kernel), strides=tuple(self.strides), padding=self.padding,
                    use_bias=False, name="conv")(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=1e-3, momentum=0.9, name="bn")(x)
        return nn.relu(x)


def _same(k: int) -> Any:
    return ((k // 2, k // 2), (k // 2, k // 2))


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(64, (1, 1), name="branch1x1")(x)
        b5 = BasicConv2d(48, (1, 1), name="branch5x5_1")(x)
        b5 = BasicConv2d(64, (5, 5), padding=_same(5), name="branch5x5_2")(b5)
        b3 = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = BasicConv2d(96, (3, 3), padding=_same(3), name="branch3x3dbl_2")(b3)
        b3 = BasicConv2d(96, (3, 3), padding=_same(3), name="branch3x3dbl_3")(b3)
        bp = _avg_pool_nopad(x)
        bp = BasicConv2d(self.pool_features, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(384, (3, 3), strides=(2, 2), name="branch3x3")(x)
        bd = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(96, (3, 3), padding=_same(3), name="branch3x3dbl_2")(bd)
        bd = BasicConv2d(96, (3, 3), strides=(2, 2), name="branch3x3dbl_3")(bd)
        bp = _max_pool(x, 3, 2)
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        c7 = self.channels_7x7
        p17 = ((0, 0), (3, 3))
        p71 = ((3, 3), (0, 0))
        b1 = BasicConv2d(192, (1, 1), name="branch1x1")(x)
        b7 = BasicConv2d(c7, (1, 1), name="branch7x7_1")(x)
        b7 = BasicConv2d(c7, (1, 7), padding=p17, name="branch7x7_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=p71, name="branch7x7_3")(b7)
        bd = BasicConv2d(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = BasicConv2d(c7, (7, 1), padding=p71, name="branch7x7dbl_2")(bd)
        bd = BasicConv2d(c7, (1, 7), padding=p17, name="branch7x7dbl_3")(bd)
        bd = BasicConv2d(c7, (7, 1), padding=p71, name="branch7x7dbl_4")(bd)
        bd = BasicConv2d(192, (1, 7), padding=p17, name="branch7x7dbl_5")(bd)
        bp = _avg_pool_nopad(x)
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        p17 = ((0, 0), (3, 3))
        p71 = ((3, 3), (0, 0))
        b3 = BasicConv2d(192, (1, 1), name="branch3x3_1")(x)
        b3 = BasicConv2d(320, (3, 3), strides=(2, 2), name="branch3x3_2")(b3)
        b7 = BasicConv2d(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = BasicConv2d(192, (1, 7), padding=p17, name="branch7x7x3_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=p71, name="branch7x7x3_3")(b7)
        b7 = BasicConv2d(192, (3, 3), strides=(2, 2), name="branch7x7x3_4")(b7)
        bp = _max_pool(x, 3, 2)
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Final inception block; ``pool="avg"`` for E1, ``"max"`` for the FID E2 quirk."""

    pool: str = "avg"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        p13 = ((0, 0), (1, 1))
        p31 = ((1, 1), (0, 0))
        b1 = BasicConv2d(320, (1, 1), name="branch1x1")(x)
        b3 = BasicConv2d(384, (1, 1), name="branch3x3_1")(x)
        b3 = jnp.concatenate(
            [
                BasicConv2d(384, (1, 3), padding=p13, name="branch3x3_2a")(b3),
                BasicConv2d(384, (3, 1), padding=p31, name="branch3x3_2b")(b3),
            ],
            axis=-1,
        )
        bd = BasicConv2d(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(384, (3, 3), padding=_same(3), name="branch3x3dbl_2")(bd)
        bd = jnp.concatenate(
            [
                BasicConv2d(384, (1, 3), padding=p13, name="branch3x3dbl_3a")(bd),
                BasicConv2d(384, (3, 1), padding=p31, name="branch3x3dbl_3b")(bd),
            ],
            axis=-1,
        )
        if self.pool == "max":
            bp = _max_pool(x, 3, 1, same=True)
        else:
            bp = _avg_pool_nopad(x)
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3Features(nn.Module):
    """Full FID InceptionV3; returns {64, 192, 768, 2048} feature taps (NHWC in)."""

    @nn.compact
    def __call__(self, x: Array) -> Dict[int, Array]:
        feats: Dict[int, Array] = {}
        x = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
        x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = BasicConv2d(64, (3, 3), padding=_same(3), name="Conv2d_2b_3x3")(x)
        x = _max_pool(x, 3, 2)
        feats[64] = x
        x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = _max_pool(x, 3, 2)
        feats[192] = x
        x = InceptionA(32, name="Mixed_5b")(x)
        x = InceptionA(64, name="Mixed_5c")(x)
        x = InceptionA(64, name="Mixed_5d")(x)
        x = InceptionB(name="Mixed_6a")(x)
        x = InceptionC(128, name="Mixed_6b")(x)
        x = InceptionC(160, name="Mixed_6c")(x)
        x = InceptionC(160, name="Mixed_6d")(x)
        x = InceptionC(192, name="Mixed_6e")(x)
        feats[768] = x
        x = InceptionD(name="Mixed_7a")(x)
        x = InceptionE(pool="avg", name="Mixed_7b")(x)
        x = InceptionE(pool="max", name="Mixed_7c")(x)
        pooled = jnp.mean(x, axis=(1, 2))  # global average pool -> (N, 2048)
        feats[2048] = pooled
        # TF-inception 1008-class fc head; 'logits_unbiased' is the pre-bias
        # product (torch-fidelity feature_extractor_inceptionv3 semantics)
        logits_unbiased = nn.Dense(NUM_LOGITS, use_bias=False, name="fc")(pooled)
        fc_bias = self.param("fc_bias", nn.initializers.zeros, (NUM_LOGITS,))
        feats["logits_unbiased"] = logits_unbiased
        feats["logits"] = logits_unbiased + fc_bias
        return feats


def init_inception_params(key: Optional[Array] = None, image_size: int = 299) -> Dict[str, Any]:
    """Random-init param/batch-stats tree (architecture-correct shapes)."""
    key = key if key is not None else jax.random.PRNGKey(0)
    module = InceptionV3Features()
    variables = module.init(key, jnp.zeros((1, image_size, image_size, 3), dtype=jnp.float32))
    return {"params": variables["params"], "batch_stats": variables.get("batch_stats", {})}


def params_from_torch_fidelity_state_dict(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Convert a torch-fidelity ``FeatureExtractorInceptionV3.state_dict()`` to our tree.

    The reference auto-loads exactly that network (reference image/fid.py:30-44);
    this is the offline conversion path the module docstring promises, mirroring
    ``models/lpips.py:params_from_torch_state_dict``. Accepts the state dict as
    numpy arrays (or anything ``jnp.asarray`` takes) keyed by the torch module
    paths, e.g. ``Mixed_5b.branch1x1.conv.weight``. Mapping:

    - ``<block>.conv.weight`` (OIHW) -> ``params/<block>/conv/kernel`` (HWIO)
    - ``<block>.bn.{weight,bias}`` -> ``params/<block>/bn/{scale,bias}``
    - ``<block>.bn.running_{mean,var}`` -> ``batch_stats/<block>/bn/{mean,var}``
    - ``fc.weight`` (1008, 2048) -> ``params/fc/kernel`` (2048, 1008);
      ``fc.bias`` -> ``params/fc_bias`` (the split head that exposes
      torch-fidelity's pre-bias ``logits_unbiased`` tap)

    Procedure (offline, outside this zero-egress environment)::

        net = torch_fidelity.feature_extractor_inceptionv3.FeatureExtractorInceptionV3(
            'inception-v3-compat', ['2048'])
        sd = {k: v.numpy() for k, v in net.state_dict().items()}
        params = params_from_torch_fidelity_state_dict(sd)
        # persist with orbax:
        import orbax.checkpoint as ocp
        ocp.StandardCheckpointer().save(path, params)

    The result's structure is validated leaf-by-leaf (names and shapes) against
    the architecture's init tree; missing or mismatched entries raise.
    """
    # shapes only — eval_shape traces init without running the 21.8M-param
    # forward pass a real init would pay
    abstract = jax.eval_shape(
        InceptionV3Features().init, jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3), dtype=jnp.float32)
    )
    template = {"params": abstract["params"], "batch_stats": abstract.get("batch_stats", {})}
    params: Dict[str, Any] = {}
    batch_stats: Dict[str, Any] = {}
    converted: Dict[str, Any] = {"params": params, "batch_stats": batch_stats}
    suffix_map = {
        "conv.weight": ("params", "kernel", lambda w: jnp.transpose(jnp.asarray(w, jnp.float32), (2, 3, 1, 0))),
        "bn.weight": ("params", "scale", lambda w: jnp.asarray(w, jnp.float32)),
        "bn.bias": ("params", "bias", lambda w: jnp.asarray(w, jnp.float32)),
        "bn.running_mean": ("batch_stats", "mean", lambda w: jnp.asarray(w, jnp.float32)),
        "bn.running_var": ("batch_stats", "var", lambda w: jnp.asarray(w, jnp.float32)),
    }
    for key, value in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        if key == "fc.weight":
            params["fc"] = {"kernel": jnp.transpose(jnp.asarray(value, jnp.float32), (1, 0))}
            continue
        if key == "fc.bias":
            params["fc_bias"] = jnp.asarray(value, jnp.float32)
            continue
        for suffix, (collection, leaf, fn) in suffix_map.items():
            if key.endswith("." + suffix):
                module_path = key[: -len(suffix) - 1].split(".")  # e.g. [Mixed_5b, branch1x1]
                node = converted[collection]
                for part in module_path:
                    node = node.setdefault(part, {})
                sub = "conv" if suffix.startswith("conv") else "bn"
                node.setdefault(sub, {})[leaf] = fn(value)
                break
        else:
            raise ValueError(f"Unrecognised torch-fidelity state-dict key: {key!r}")

    def _check(tmpl: Any, got: Any, path: str) -> None:
        if isinstance(tmpl, dict):
            if not isinstance(got, dict):
                raise ValueError(f"Missing subtree {path!r} in converted params")
            missing = set(tmpl) - set(got)
            extra = set(got) - set(tmpl)
            if missing or extra:
                raise ValueError(f"At {path!r}: missing {sorted(missing)}, unexpected {sorted(extra)}")
            for k in tmpl:
                _check(tmpl[k], got[k], f"{path}/{k}")
        elif tuple(jnp.shape(tmpl)) != tuple(jnp.shape(got)):
            raise ValueError(f"Shape mismatch at {path!r}: expected {jnp.shape(tmpl)}, got {jnp.shape(got)}")

    _check(template, converted, "")
    return converted


def inception_feature_extractor(
    params: Optional[Dict[str, Any]] = None,
    feature_dim=2048,
):
    """Build the ``imgs -> (N, F)`` callable FID/KID/IS/MiFID consume.

    Input contract matches the reference (image/fid.py:194-199): NCHW images in
    [0, 255] (uint8 or float — the metrics' ``normalize=True`` path already
    rescales [0,1] floats to this range before calling the extractor). Images
    are TF-1.x-bilinear resized to 299x299 and normalised as ``(x - 128)/128``
    (torch-fidelity's exact input scaling) before the network.

    ``feature_dim``: one of 64/192/768/2048 (feature taps) or
    ``"logits"``/``"logits_unbiased"`` (the 1008-class head InceptionScore uses).
    """
    if feature_dim not in VALID_FEATURE_KEYS:
        raise ValueError(f"Argument `feature_dim` must be one of {VALID_FEATURE_KEYS}, got {feature_dim}")
    if params is None:
        params = init_inception_params()
    module = InceptionV3Features()

    def extract(imgs: Array) -> Array:
        x = (jnp.asarray(imgs).astype(jnp.float32) - 128.0) / 128.0
        x = tf1_bilinear_resize(x, 299)
        x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC
        feats = module.apply(
            {"params": params["params"], "batch_stats": params.get("batch_stats", {})}, x
        )
        f = feats[feature_dim]
        if f.ndim == 4:  # spatial taps: global average, like the reference's map stage
            f = jnp.mean(f, axis=(1, 2))
        return f

    return extract


def resolve_inception_extractor(
    metric_name: str,
    feature_extractor,
    inception_params: Optional[Dict[str, Any]],
    feature_dim=2048,
):
    """Shared fallback for FID/KID/IS/MiFID: callable wins; otherwise build the
    built-in InceptionV3 from ``inception_params``; otherwise raise."""
    if feature_extractor is not None:
        return feature_extractor
    if inception_params is None:
        raise ModuleNotFoundError(
            f"{metric_name} requires either a `feature_extractor` callable mapping images to"
            " (N, F) features, or `inception_params` for the built-in flax InceptionV3"
            " (torchmetrics_tpu.models.inception). Bundled pretrained weights are not"
            " available in this environment."
        )
    return inception_feature_extractor(inception_params, feature_dim=feature_dim)


def resolve_feature_argument(
    metric_name: str,
    feature,
    feature_extractor,
    inception_params: Optional[Dict[str, Any]],
    default_dim=2048,
):
    """Reference-compatible ``feature`` argument for FID/KID/IS/MiFID.

    The reference's first constructor argument (reference image/fid.py:298,
    kid.py:176-178, inception.py:108-110, mifid.py:156-158) is
    ``feature: Union[str, int, Module]`` — an integer/string selecting the
    InceptionV3 tap, or a module used as the extractor. Here a callable plays
    the module role; int/str taps route to the built-in flax InceptionV3
    (which needs ``inception_params``). Returns ``(extractor, feature_dim)``
    where ``feature_dim`` is None when a callable was supplied (its output
    width is the caller's contract).
    """
    if feature is not None and feature_extractor is not None:
        raise ValueError(f"{metric_name}: pass either `feature` or `feature_extractor`, not both")
    if feature is not None and callable(feature):
        return feature, None
    feature_dim = default_dim if feature is None else feature
    if feature_dim not in VALID_FEATURE_KEYS:
        raise ValueError(
            f"Integer input to argument `feature` must be one of {list(VALID_FEATURE_DIMS)},"
            f" string input must be 'logits' or 'logits_unbiased', but got {feature_dim}"
        )
    extractor = resolve_inception_extractor(
        metric_name, feature_extractor, inception_params, feature_dim=feature_dim
    )
    if feature_extractor is not None:
        return extractor, None
    return extractor, feature_dim
