"""Streaming windowed metric state: O(1) window advance on a ring axis.

Always-on production monitoring asks time-scoped questions — "accuracy over
the last N minutes", tumbling per-interval aggregates, per-tenant watermarks —
that a single monotonically-growing accumulator cannot answer. The naive fix
(re-accumulate the last W intervals' worth of batches on every interval tick)
is O(W) per advance and keeps every raw batch alive. This module instead
stacks **W per-window sub-states along a second leading axis** — the same
DrJAX-style map-over-independent-state move the lane axis made (PAPERS.md,
lanes.py) — and makes both halves of windowing constant-cost, shape-stable
dispatches:

- **Advance is O(1)**: a monotonic window clock (``window_head``, an int32
  state field — *data*, never a shape) names the open window; the ring slot
  ``head % W`` houses it. Advancing rotates the head and masked-resets ONLY
  the retiring slot to defaults via a one-hot ``where`` — one donated,
  jit-cached dispatch whose executable is identical for every head value, so
  a 1k-lane × 64-window tumbling setup advances with **zero recompiles** and
  no per-window work.
- **Sliding reads fold the live ring** through the segment-merge families of
  ``parallel.reshard.merge_folded``: dead slots (not yet opened) are masked
  to ``reduction_identity`` and the window axis collapses in one reduction
  (``parallel.sync.fold_window_slots``) — ``sum``/``mean`` segments add,
  ``max``/``min`` take the extremum — bit-exact to re-accumulating the live
  windows from scratch.
- **Watermarks**: ``update_window(k, batch)`` routes a late event into its
  owning (still-open) window as long as ``clock - k <= lateness``; older
  events are dropped with a fault breadcrumb and counted
  (``windows.dropped_late``), never silently. Late admits bump
  ``windows.late_events`` and observe ``windows.lateness_us`` (time since
  the owning window closed).
- **Window-aligned async reads**: ``compute_async()`` snapshots the ring by
  reference *and pins the submit-time clock*, so a read submitted at window
  k's close resolves bit-exact to window k's close on the read pipeline even
  while later windows advance underneath it (docs/ASYNC.md).

Composition
    - ``LanedMetric(WindowedMetric(m), ...)`` stacks the window axis UNDER
      the lane axis — state is ``(lanes, W, *field)`` — and the unmodified
      laned gather/vmap/scatter dispatch advances every session's open
      window in one donated call, because the head-slot routing lives inside
      the windowed ``functional_update`` on *traced* per-lane heads.
      ``LanedMetric.advance_windows()`` rotates every lane's ring at once.
    - ``reduce="deferred"``: windowed states shard like any fixed-shape
      state — ``(num_shards, W, *field)`` — and the window clock
      (``fx="max"``) folds exactly through the canonical seam
      (``parallel/reshard.py``), so checkpoints and elastic restores carry
      the ring per-window.

Metrics holding list ("cat") accumulators, callable or ``None`` reductions
cannot stack a ring axis (no identity-masked fold exists); those fall back to
an exact eager per-window path — every windowing guarantee holds, only the
single-dispatch advance does not (see docs/STREAMING.md).
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.parallel.sync import fold_window_slots, live_window_mask
from torchmetrics_tpu.utils.exceptions import StateCorruptionError, TorchMetricsUserError
from torchmetrics_tpu.utils.prints import rank_zero_warn

__all__ = [
    "DEFAULT_WINDOW",
    "WINDOW_ELIGIBLE_REDUCTIONS",
    "WindowedCollection",
    "WindowedMetric",
    "window_eligible",
]

DEFAULT_WINDOW = 8

#: reduction families whose states can carry a compiled ring axis: fixed-shape
#: arrays with an identity-masked fold (parallel.sync.fold_window_slots).
#: "cat"/None/callables fall back to the eager per-window path with a warning.
WINDOW_ELIGIBLE_REDUCTIONS = ("sum", "mean", "max", "min")


def window_eligible(defaults: Dict[str, Any], reductions: Dict[str, Any]) -> bool:
    """Whether a metric's declared states can stack a compiled ring axis:
    every state a fixed-shape array under a ``sum``/``mean``/``max``/``min``
    reduction (the :data:`WINDOW_ELIGIBLE_REDUCTIONS` families)."""
    for name, default in defaults.items():
        if isinstance(default, list):
            return False
        if reductions.get(name) not in WINDOW_ELIGIBLE_REDUCTIONS:
            return False
    return True


def _encode_json_blob(payload: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(json.dumps(payload, sort_keys=True).encode("utf-8"), dtype=np.uint8).copy()


def _decode_json_blob(blob: Any, what: str) -> Dict[str, Any]:
    try:
        return json.loads(np.asarray(blob, dtype=np.uint8).tobytes().decode("utf-8"))
    except Exception as err:
        raise obs.flighted(
            StateCorruptionError(f"{what} blob is unreadable ({type(err).__name__}: {err})"),
            domain="windows",
        ) from err


def _now_us() -> int:
    return time.monotonic_ns() // 1000


class WindowedMetric(Metric):
    """W per-window sub-states of ``inner`` stacked on a ring axis.

    Args:
        inner: the metric to window. A detached clone is held — the wrapper
            only ever calls its pure ``functional_update``/``functional_compute``.
        window: number of ring slots W (the sliding-window span in windows).
        lateness: watermark bound, in windows: an event for window ``k`` is
            still admitted while ``clock - k <= lateness`` (and the slot is
            live); older events are dropped with a breadcrumb. Must satisfy
            ``0 <= lateness < window``.
        kwargs: forwarded to :class:`~torchmetrics_tpu.Metric`.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SumMetric
        >>> from torchmetrics_tpu.windows import WindowedMetric
        >>> win = WindowedMetric(SumMetric(), window=4)
        >>> win.update(jnp.asarray([1.0, 2.0]))
        >>> win.advance()  # returns the new window clock
        1
        >>> win.update(jnp.asarray([10.0]))
        >>> float(win.compute())  # sliding aggregate over the live ring
        13.0
        >>> float(win.compute_window(0)), float(win.compute_window(1))
        (3.0, 10.0)
    """

    full_state_update: Optional[bool] = False

    #: executor bucket-padding duplicates rows; the head-slot scatter makes a
    #: duplicated row land twice in the SAME window sub-state (unlike a plain
    #: metric, where inner semantics decide) — never bucket windowed dispatches
    _executor_bucketable = False

    #: reserved state key carrying the ring geometry + host clock through
    #: state()/load_state as a uint8 JSON blob leaf (the lane-directory idiom)
    _WINDOW_META_KEY = "_window_meta"
    _RESERVED_STATE_KEYS = Metric._RESERVED_STATE_KEYS + (_WINDOW_META_KEY,)

    #: wrapper-owned state riding next to the ring-stacked inner fields: the
    #: monotonic window clock. ``fx="max"`` folds it exactly across lanes,
    #: shards and elastic resharding (identical replicas → the value itself)
    _WINDOW_AUX_FIELDS = ("window_head",)

    def __init__(
        self,
        inner: Metric,
        window: int = DEFAULT_WINDOW,
        lateness: int = 0,
        **kwargs: Any,
    ) -> None:
        if not isinstance(inner, Metric):
            raise ValueError(f"WindowedMetric wraps a Metric, got {type(inner).__name__}")
        if isinstance(inner, WindowedMetric):
            raise ValueError("WindowedMetric cannot wrap another WindowedMetric")
        from torchmetrics_tpu.lanes import LanedMetric

        if isinstance(inner, LanedMetric):
            raise ValueError(
                "window the metric first, then lane it: LanedMetric(WindowedMetric(m))"
                " stacks the window axis under the lane axis"
            )
        window = int(window)
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        lateness = int(lateness)
        if not 0 <= lateness < window:
            raise ValueError(f"lateness must satisfy 0 <= lateness < window={window}, got {lateness}")
        # the wrapper's collectives ship the inner states stacked on a ring
        # axis: inherit the inner sync_precision policy unless overridden
        kwargs.setdefault("sync_precision", inner.__dict__.get("sync_precision"))
        kwargs.setdefault("sync_quant_bits", inner.__dict__.get("sync_quant_bits"))
        kwargs.setdefault("sync_quant_block", inner.__dict__.get("sync_quant_block"))
        super().__init__(**kwargs)
        inner = inner.clone()
        inner.__dict__["_executor_enabled"] = False  # used functionally only
        self.__dict__["_inner"] = inner
        self.window = window
        self.lateness = lateness
        compiled = window_eligible(inner._defaults, inner._reductions)
        self.__dict__["_compiled_windows"] = compiled
        if compiled:
            for name, default in inner._defaults.items():
                self.add_state(
                    name,
                    self._stacked_default(default, window),
                    dist_reduce_fx=inner._reductions[name],
                    sync_precision=inner._sync_precisions.get(name),
                )
            self.add_state("window_head", jnp.zeros((), jnp.int32), dist_reduce_fx="max")
        else:
            rank_zero_warn(
                f"{type(inner).__name__} holds list/'cat'/custom-reduction state —"
                " no compiled ring axis exists for it; WindowedMetric falls back to"
                " the exact eager per-window path (O(1) advance still holds, the"
                " single-dispatch speedup does not; see docs/STREAMING.md)"
            )
            self.__dict__["_window_states"] = [inner.init_state() for _ in range(window)]
            self.__dict__["_window_counts"] = [0] * window
        self.__dict__["_host_clock"] = 0
        self.__dict__["_close_times_us"] = {}
        self.__dict__["_advance_fns"] = {}

    # ------------------------------------------------------------- properties
    @property
    def inner(self) -> Metric:
        """The wrapped (detached) metric."""
        return self.__dict__["_inner"]

    @property
    def clock(self) -> int:
        """The monotonic index of the OPEN window (host mirror of
        ``window_head`` — authoritative for watermark admission, so the hot
        path never syncs the device clock)."""
        return self.__dict__["_host_clock"]

    @property
    def head_slot(self) -> int:
        """Ring slot housing the open window (``clock % window``)."""
        return self.__dict__["_host_clock"] % self.window

    @property
    def live_windows(self) -> Tuple[int, int]:
        """Inclusive ``(oldest, newest)`` absolute indices of live windows."""
        clock = self.__dict__["_host_clock"]
        return (max(0, clock - self.window + 1), clock)

    def window_spec(self) -> Dict[str, Any]:
        """Ring geometry + clock, exported into checkpoint manifests
        (io/checkpoint.py "window block")."""
        clock = self.__dict__["_host_clock"]
        return {
            "window": self.window,
            "lateness": self.lateness,
            "clock": clock,
            "head": clock % self.window,
            "compiled": self._compiled_windows,
        }

    @property
    def _compiled_windows(self) -> bool:
        return self.__dict__["_compiled_windows"]

    @staticmethod
    def _stacked_default(default: Any, window: int) -> jnp.ndarray:
        arr = jnp.asarray(default)
        return jnp.broadcast_to(arr[None], (window,) + arr.shape)

    def _inner_fields(self) -> List[str]:
        return list(self.inner._defaults)

    def _executor_identity(self) -> str:
        """Joins the executor's cross-process cache key: the compiled
        computation is the INNER metric's update on a ring row, so two
        windowed wrappers with identical stacked specs but different inner
        metrics must never share a persisted executable."""
        import sys

        from torchmetrics_tpu.ops import compile_cache

        inner = self.inner
        cls = type(inner)
        mod = sys.modules.get(cls.__module__)
        return f"{cls.__module__}.{cls.__qualname__}@{compile_cache.source_hash(mod or cls)}"

    def _trace_config(self) -> tuple:
        """The inner metric's trace config plus the ring geometry: a windowed
        trace gathers/scatters a window axis a plain trace does not have, so
        they must never share a persisted executable."""
        return (
            tuple(super()._trace_config())
            + tuple(self.inner._trace_config())
            + (f"windows={self.window}",)
        )

    # ------------------------------------------------------------ update path
    def update(self, *args: Any, window: Optional[Any] = None, **kwargs: Any) -> None:
        """Advance the OPEN window's sub-state with one batch.

        ``window`` (normally left None) targets an explicit ABSOLUTE window
        index instead — the late-event path. Callers use
        :meth:`update_window`, which enforces the watermark host-side and
        passes the index as a traced int32 scalar so every window value runs
        the SAME executable (data, not shape — zero recompiles).
        """
        if not self._compiled_windows:
            self._update_eager(args, kwargs, window)
            return
        inner = self.inner
        fields = self._inner_fields()
        states = {f: self._state[f] for f in fields}
        if window is None:
            slot = jnp.mod(self._state["window_head"], self.window)
        else:
            slot = jnp.mod(jnp.asarray(window, jnp.int32), self.window)
        row = {f: jnp.take(v, slot, axis=0) for f, v in states.items()}
        with obs.device_span(obs.SPAN_UPDATE, suffix=type(inner).__name__):
            new_row = inner.functional_update(row, *args, **kwargs)
        for f in fields:
            self._state[f] = states[f].at[slot].set(new_row[f])

    def _update_eager(self, args: Tuple[Any, ...], kwargs: Dict[str, Any], window: Optional[Any]) -> None:
        inner = self.inner
        k = self.__dict__["_host_clock"] if window is None else int(window)
        slot = k % self.window
        # staged then committed: an inner update raising mid-way leaves the
        # window exactly as it was (transactional, like the array path)
        staged = inner.functional_update(self.__dict__["_window_states"][slot], *args, **kwargs)
        self.__dict__["_window_states"][slot] = staged
        self.__dict__["_window_counts"][slot] += 1

    def update_window(self, k: int, *args: Any, **kwargs: Any) -> bool:
        """Route a batch into ABSOLUTE window ``k``, enforcing the watermark.

        Returns True when the batch landed. An event older than the lateness
        bound (or whose slot has been recycled) is DROPPED with a fault
        breadcrumb and the ``windows.dropped_late`` counter — degraded, loud,
        never an exception (chaos parity with every other ingest seam).
        Events for future windows raise: the clock only moves via
        :meth:`advance`.
        """
        k = int(k)
        clock = self.__dict__["_host_clock"]
        if k > clock:
            raise TorchMetricsUserError(
                f"window {k} is ahead of the clock ({clock}); advance() opens windows"
            )
        age = clock - k
        if age > 0:
            if age > self.lateness or age >= self.window:
                obs.counter_inc("windows.dropped_late")
                obs.fault_breadcrumb(
                    "window_late_drop",
                    domain="windows",
                    data={"window": k, "clock": clock, "age": age, "lateness": self.lateness},
                )
                return False
            obs.counter_inc("windows.late_events")
            close = self.__dict__["_close_times_us"].get(k)
            if close is not None:
                obs.histogram_observe("windows.lateness_us", _now_us() - close)
        if self._compiled_windows:
            self.update(*args, window=jnp.asarray(k, jnp.int32), **kwargs)
        else:
            self.update(*args, window=k, **kwargs)
        return True

    # ----------------------------------------------------------- ring advance
    def advance(self, n: int = 1) -> int:
        """Close the open window and open the next, ``n`` times: rotate the
        head and masked-reset ONLY the retiring slot — one donated, jit-cached
        dispatch per step whose executable never depends on the head value
        (the slot one-hot is computed from the traced clock). Returns the new
        clock."""
        for _ in range(int(n)):
            self._advance_once()
        return self.__dict__["_host_clock"]

    def _advance_once(self) -> None:
        clock = self.__dict__["_host_clock"]
        with obs.span(
            obs.SPAN_WINDOWS,
            suffix=type(self.inner).__name__,
            histogram="windows.advance_us",
            window=self.window,
        ):
            if self._compiled_windows:
                donate = not self.__dict__.get("_state_escaped")
                fn = self._advance_fn(donate)
                fields = self._inner_fields() + ["window_head"]
                new_states = fn({f: self._state[f] for f in fields})
                for f in fields:
                    self._state[f] = new_states[f]
                if not donate:
                    # the jit outputs are fresh buffers: no external aliases
                    self.__dict__["_state_escaped"] = False
            else:
                slot = (clock + 1) % self.window
                self.__dict__["_window_states"][slot] = self.inner.init_state()
                self.__dict__["_window_counts"][slot] = 0
        self.__dict__["_host_clock"] = clock + 1
        closes = self.__dict__["_close_times_us"]
        closes[clock] = _now_us()
        horizon = clock - self.lateness - 1
        for old in [w for w in closes if w < horizon]:
            closes.pop(old)
        self._computed = None
        obs.counter_inc("windows.advanced")

    def _advance_fn(self, donate: bool) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        fn = self.__dict__["_advance_fns"].get(bool(donate))
        if fn is None:
            window = self.window
            inner = self.inner
            defaults = {f: jnp.asarray(d) for f, d in inner._defaults.items()}

            def body(states: Dict[str, Any]) -> Dict[str, Any]:
                head = states["window_head"] + 1
                slot = jnp.mod(head, window)
                out: Dict[str, Any] = {"window_head": head}
                for f, v in states.items():
                    if f == "window_head":
                        continue
                    # scatter ONLY the retiring slot back to the identity —
                    # with a donated input this is an in-place
                    # dynamic-update-slice, so advance cost is independent
                    # of W (touching the whole ring via a masked where
                    # would scale the memory traffic with W)
                    out[f] = v.at[slot].set(defaults[f])
                return out

            fn = jax.jit(body, donate_argnums=0) if donate else jax.jit(body)
            self.__dict__["_advance_fns"][bool(donate)] = fn
        return fn

    # ------------------------------------------------------------- read paths
    def compute(self) -> Any:
        """Sliding aggregate over the live ring: dead slots masked to the
        reduction identity, live slots folded per segment-merge semantics
        (``parallel.sync.fold_window_slots``), then the inner compute."""
        inner = self.inner
        if not self._compiled_windows:
            folded = self._fold_eager()
            return inner.functional_compute(folded if folded is not None else inner.init_state())
        folded = self._fold_windows(
            {f: self._state[f] for f in self._inner_fields()}, self._state["window_head"]
        )
        return inner.functional_compute(folded)

    def _fold_windows(self, states: Dict[str, Any], head: Any) -> Dict[str, Any]:
        inner = self.inner
        live = live_window_mask(head, self.window)
        return {f: fold_window_slots(v, inner._reductions.get(f), live) for f, v in states.items()}

    def _fold_eager(self) -> Optional[Dict[str, Any]]:
        inner = self.inner
        lo, hi = self.live_windows
        folded, count = None, 0
        for k in range(lo, hi + 1):
            slot = k % self.window
            st = self.__dict__["_window_states"][slot]
            c = self.__dict__["_window_counts"][slot]
            if folded is None:
                folded, count = st, c
            else:
                # count-weighted merge reproduces the unwindowed running-mean
                # formula exactly for "mean" states; other families ignore it
                folded = inner.merge_states(folded, st, counts=(max(count, 1), max(c, 1)))
                count += c
        return folded

    def compute_window(self, k: int) -> Any:
        """One window's ``compute()`` value — valid while its slot is live
        (``clock - window < k <= clock``)."""
        k = int(k)
        clock = self.__dict__["_host_clock"]
        if not clock - self.window < k <= clock:
            raise TorchMetricsUserError(
                f"window {k} is not live (clock={clock}, ring holds the last {self.window})"
            )
        inner = self.inner
        slot = k % self.window
        if not self._compiled_windows:
            return inner.functional_compute(self.__dict__["_window_states"][slot])
        row = {f: jnp.take(self._state[f], slot, axis=0) for f in self._inner_fields()}
        return inner.functional_compute(row)

    # ----------------------------------------------------- asynchronous reads
    def _read_inner_clone(self) -> Metric:
        """Detached clone of ``inner`` for worker-side ``functional_compute``
        (the live inner swaps its ``_state`` during traces — lanes.py rule)."""
        cached = self.__dict__.get("_inner_clone_cache")
        if cached is None:
            cached = self.inner.clone()
            cached.__dict__["_executor_enabled"] = False
            self.__dict__["_inner_clone_cache"] = cached
        return cached

    def _prepare_async_read(self) -> Callable[[], Any]:
        """Window-aligned asynchronous read (docs/ASYNC.md): the caller
        snapshots the ring by reference AND pins the submit-time clock, so
        the worker folds exactly the windows that were live at submission —
        a read submitted at window k's close resolves bit-exact to window
        k's close, however far the ring advances before it runs (the escape
        flag routes subsequent advances through the non-donating dispatch,
        keeping the snapshot buffers intact)."""
        from torchmetrics_tpu.ops import async_read as _async

        cached = self._computed
        if cached is not None:
            return lambda: _async.materialize(cached)
        if not self._compiled_windows or bool(self.distributed_available_fn()):
            obs.counter_inc("reads.inline_compute")
            value = self.compute()
            return lambda: _async.materialize(value)
        self._fold_pending()  # deferred shards: dispatch the fold, don't wait
        snapshot = self._copy_state_dict()  # by-reference; marks state escaped
        flags = self._capture_read_flags()
        clock = self.__dict__["_host_clock"]
        inner_clone = self._read_inner_clone()
        return lambda: self._async_window_job(snapshot, flags, clock, inner_clone)

    def _async_window_job(
        self, snapshot: Dict[str, Any], flags: Dict[str, Any], clock: int, inner_clone: Metric
    ) -> Any:
        """WORKER-SIDE: fold the pinned-clock ring snapshot, compute on a
        detached inner clone, materialize, guarded cache write-back."""
        from torchmetrics_tpu.ops import async_read as _async

        live = live_window_mask(jnp.asarray(clock, jnp.int32), self.window)
        folded = {
            f: fold_window_slots(snapshot[f], inner_clone._reductions.get(f), live)
            for f in self._inner_fields()
        }
        value = _async.materialize(inner_clone.functional_compute(folded))
        if (
            self.__dict__.get("_update_count") == flags["count"]
            and flags["cache"]
            and self.__dict__.get("_host_clock") == clock
            and self.__dict__.get("_computed") is None
        ):
            self.__dict__["_computed"] = value
            if self.__dict__.get("_update_count") != flags["count"]:
                self.__dict__["_computed"] = None  # an update landed mid-write
        return value

    # ------------------------------------------------------------- durability
    def _window_meta_blob(self) -> np.ndarray:
        return _encode_json_blob(
            {
                "window": self.window,
                "lateness": self.lateness,
                "clock": self.__dict__["_host_clock"],
            }
        )

    def state(self) -> Dict[str, Any]:
        """State export carrying the ring geometry + host clock under the
        reserved ``"_window_meta"`` key (a uint8 JSON blob the snapshot store
        persists as an ordinary leaf) — restores re-anchor the watermark
        clock without a device sync."""
        if self._compiled_windows:
            out = super().state()
            out[self._WINDOW_META_KEY] = self._window_meta_blob()
            return out
        out: Dict[str, Any] = {
            f"window_{i:05d}": {
                **self.__dict__["_window_states"][i],
                self._STATE_COUNT_KEY: self.__dict__["_window_counts"][i],
            }
            for i in range(self.window)
        }
        out[self._WINDOW_META_KEY] = self._window_meta_blob()
        return out

    def load_state(
        self,
        state: Dict[str, Any],
        update_count: Optional[int] = None,
        validate: str = "strict",
        check_finite: bool = False,
        sharded: Optional[bool] = None,
    ) -> None:
        """Install a windowed export: the meta blob re-anchors the clock and
        is validated against this instance's ring geometry (a W=64 snapshot
        never silently reinstalls into a W=8 ring)."""
        if not isinstance(state, dict):
            raise obs.flighted(
                StateCorruptionError(
                    f"{type(self).__name__}: state must be a dict, got {type(state).__name__}"
                ),
                domain="windows",
            )
        state = dict(state)
        blob = state.pop(self._WINDOW_META_KEY, None)
        meta = _decode_json_blob(blob, f"{type(self).__name__} window meta") if blob is not None else None
        if meta is not None and validate != "off" and int(meta.get("window", self.window)) != self.window:
            raise obs.flighted(
                StateCorruptionError(
                    f"{type(self).__name__}: snapshot carries a {meta['window']}-slot ring,"
                    f" this instance is configured for {self.window}"
                ),
                domain="windows",
            )
        if not self._compiled_windows:
            self._load_state_eager(state, validate=validate, check_finite=check_finite)
        else:
            super().load_state(
                state,
                update_count=update_count,
                validate=validate,
                check_finite=check_finite,
                sharded=sharded,
            )
        if meta is not None:
            clock = int(meta.get("clock", 0))
        elif self._compiled_windows:
            head = np.asarray(self._state["window_head"])
            clock = int(head.max())  # sharded exports stack the clock; max is exact
        else:
            clock = 0
        self.__dict__["_host_clock"] = clock
        self.__dict__["_close_times_us"] = {}

    def _load_state_eager(self, state: Dict[str, Any], validate: str, check_finite: bool) -> None:
        inner = self.inner
        keys = sorted(k for k in state if isinstance(k, str) and k.startswith("window_"))
        if len(keys) != self.window:
            raise obs.flighted(
                StateCorruptionError(
                    f"{type(self).__name__}: export holds {len(keys)} window states,"
                    f" expected {self.window}"
                ),
                domain="windows",
            )
        staged, counts = [], []
        for key in keys:
            sub = dict(state[key])
            count = int(np.asarray(sub.get(self._STATE_COUNT_KEY, 0)))
            try:
                checked = inner.validate_state(sub, mode=validate, check_finite=check_finite)
            except StateCorruptionError as err:
                raise obs.flighted(
                    StateCorruptionError(f"{type(self).__name__}: {key}: {err}"), domain="windows"
                ) from err
            staged.append(
                {
                    f: (list(v) if isinstance(v, (list, tuple)) else jnp.asarray(v))
                    for f, v in checked.items()
                    if f in inner._defaults
                }
            )
            counts.append(count)
        self.__dict__["_window_states"] = staged
        self.__dict__["_window_counts"] = counts
        self._computed = None
        self._update_count = self._restored_count(None, fallback=max(counts) if counts else 1)

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        """Reset every ring slot to defaults AND rewind the clock to 0."""
        super().reset()
        self.__dict__["_host_clock"] = 0
        self.__dict__["_close_times_us"] = {}
        if not self._compiled_windows:
            inner = self.inner
            self.__dict__["_window_states"] = [inner.init_state() for _ in range(self.window)]
            self.__dict__["_window_counts"] = [0] * self.window

    # --------------------------------------------------------------- plumbing
    def __getstate__(self) -> Dict[str, Any]:
        out = super().__getstate__()
        out["_advance_fns"] = {}  # jitted closures are process-local
        out.pop("_inner_clone_cache", None)
        return out

    def __setstate__(self, state: Dict[str, Any]) -> None:
        super().__setstate__(state)
        self.__dict__.setdefault("_host_clock", 0)
        self.__dict__.setdefault("_close_times_us", {})
        self.__dict__.setdefault("_advance_fns", {})

    def __repr__(self) -> str:
        return (
            f"WindowedMetric({type(self.inner).__name__}, window={self.window},"
            f" clock={self.__dict__['_host_clock']}, lateness={self.lateness})"
        )


class WindowedCollection:
    """Windowed state over a whole metric suite: every member is a
    :class:`WindowedMetric` sharing one host clock, advanced together.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MaxMetric, SumMetric
        >>> from torchmetrics_tpu.windows import WindowedCollection
        >>> wc = WindowedCollection({"s": SumMetric(), "m": MaxMetric()}, window=4)
        >>> wc.update(jnp.asarray([1.0, 5.0]))
        >>> _ = wc.advance()
        >>> wc.update(jnp.asarray([2.0]))
        >>> {k: float(v) for k, v in sorted(wc.compute().items())}
        {'m': 5.0, 's': 8.0}
    """

    def __init__(
        self,
        metrics: Union[Dict[str, Metric], Sequence[Metric], Metric, Any],
        window: int = DEFAULT_WINDOW,
        lateness: int = 0,
        **kwargs: Any,
    ) -> None:
        from torchmetrics_tpu.collections import MetricCollection

        if isinstance(metrics, MetricCollection):
            metrics = {name: m for name, m in metrics.items(keep_base=True)}
        elif isinstance(metrics, Metric):
            metrics = {type(metrics).__name__: metrics}
        elif not isinstance(metrics, dict):
            named: Dict[str, Metric] = {}
            for m in metrics:
                name = type(m).__name__
                if name in named:
                    raise ValueError(f"Encountered two metrics both named {name}")
                named[name] = m
            metrics = named
        self.window = int(window)
        self.lateness = int(lateness)
        self._members: Dict[str, WindowedMetric] = {
            name: WindowedMetric(m, window=window, lateness=lateness, **kwargs)
            for name, m in metrics.items()
        }
        self.collection = MetricCollection(dict(self._members))

    @property
    def clock(self) -> int:
        return next(iter(self._members.values())).clock if self._members else 0

    def keys(self) -> Iterable[str]:
        return self._members.keys()

    def items(self) -> Iterable[Any]:
        return self._members.items()

    def __getitem__(self, name: str) -> WindowedMetric:
        return self._members[name]

    def laned(self, capacity: int = 1024, **kwargs: Any) -> Any:
        """A LanedCollection over the windowed members: per-tenant rings
        sharing one session table, advancing in lockstep (docs/STREAMING.md
        "Lanes: per-tenant windows")."""
        from torchmetrics_tpu.lanes import LanedCollection

        return LanedCollection(self, capacity=capacity, **kwargs)

    def window_spec(self) -> Dict[str, Any]:
        return {
            "window": self.window,
            "lateness": self.lateness,
            "clock": self.clock,
            "head": self.clock % self.window,
        }

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Advance every member's open window with one fused dispatch."""
        self.collection.update(*args, **kwargs)

    def update_window(self, k: int, *args: Any, **kwargs: Any) -> bool:
        """Route a late batch into window ``k`` for every member; returns
        whether it landed (the watermark verdict is clock-driven, so every
        member agrees)."""
        landed = True
        for m in self._members.values():
            landed = m.update_window(k, *args, **kwargs) and landed
        return landed

    def advance(self, n: int = 1) -> int:
        """Advance every member's ring; returns the new shared clock."""
        clock = 0
        for m in self._members.values():
            clock = m.advance(n)
        return clock

    def compute(self) -> Dict[str, Any]:
        return self.collection.compute()

    def compute_async(self) -> Any:
        return self.collection.compute_async()

    def compute_window(self, k: int) -> Dict[str, Any]:
        return {name: m.compute_window(k) for name, m in self._members.items()}

    def reset(self) -> None:
        self.collection.reset()

    def state(self) -> Dict[str, Any]:
        return self.collection.state()

    def load_state(self, states: Dict[str, Any], **kwargs: Any) -> None:
        self.collection.load_state(states, **kwargs)

    def __repr__(self) -> str:
        return (
            f"WindowedCollection({sorted(self._members)}, window={self.window},"
            f" clock={self.clock})"
        )
