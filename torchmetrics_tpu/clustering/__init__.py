from torchmetrics_tpu.clustering.metrics import (  # noqa: F401
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)

__all__ = [
    "AdjustedMutualInfoScore",
    "AdjustedRandScore",
    "CalinskiHarabaszScore",
    "CompletenessScore",
    "DaviesBouldinScore",
    "DunnIndex",
    "FowlkesMallowsIndex",
    "HomogeneityScore",
    "MutualInfoScore",
    "NormalizedMutualInfoScore",
    "RandScore",
    "VMeasureScore",
]
