"""Modular clustering metrics (reference clustering/*.py).

Two state patterns: label metrics concatenate preds/target; embedding metrics
concatenate data/labels. Both are ``cat`` list states (compute needs the full
assignment — there is no streaming sufficient statistic for MI-family scores).
"""
from __future__ import annotations

from typing import Any

from jax import Array

from torchmetrics_tpu.functional.clustering.extrinsic import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    completeness_score,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from torchmetrics_tpu.functional.clustering.intrinsic import (
    calinski_harabasz_score,
    davies_bouldin_score,
    dunn_index,
)
from torchmetrics_tpu.functional.clustering.utils import _validate_average_method_arg
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class _LabelClusteringMetric(Metric):
    """Base for metrics comparing two label assignments."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        self.preds.append(preds)
        self.target.append(target)

    def _compute_fn_args(self):
        return ()

    def compute(self) -> Array:
        return type(self)._fn(dim_zero_cat(self.preds), dim_zero_cat(self.target), *self._compute_fn_args())


class MutualInfoScore(_LabelClusteringMetric):
    """Mutual Info Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import MutualInfoScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = MutualInfoScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.5004
    """

    _fn = staticmethod(mutual_info_score)


class RandScore(_LabelClusteringMetric):
    """Rand Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import RandScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = RandScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.6
    """

    _fn = staticmethod(rand_score)


class AdjustedRandScore(_LabelClusteringMetric):
    """Adjusted Rand Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import AdjustedRandScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = AdjustedRandScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        -0.25
    """

    _fn = staticmethod(adjusted_rand_score)
    plot_lower_bound: float = -0.5


class FowlkesMallowsIndex(_LabelClusteringMetric):
    """Fowlkes Mallows Index (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import FowlkesMallowsIndex
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = FowlkesMallowsIndex()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.0
    """

    _fn = staticmethod(fowlkes_mallows_index)
    plot_upper_bound: float = 1.0


class HomogeneityScore(_LabelClusteringMetric):
    """Homogeneity Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import HomogeneityScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = HomogeneityScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.4744
    """

    _fn = staticmethod(homogeneity_score)
    plot_upper_bound: float = 1.0


class CompletenessScore(_LabelClusteringMetric):
    """Completeness Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import CompletenessScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = CompletenessScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.4744
    """

    _fn = staticmethod(completeness_score)
    plot_upper_bound: float = 1.0


class VMeasureScore(_LabelClusteringMetric):
    """V Measure Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import VMeasureScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = VMeasureScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.4744
    """

    _fn = staticmethod(v_measure_score)
    plot_upper_bound: float = 1.0

    def __init__(self, beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(beta, (int, float)) and beta > 0):
            raise ValueError(f"Argument `beta` should be a positive float. Got {beta}.")
        self.beta = beta

    def _compute_fn_args(self):
        return (self.beta,)


class NormalizedMutualInfoScore(_LabelClusteringMetric):
    """Normalized Mutual Info Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import NormalizedMutualInfoScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = NormalizedMutualInfoScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.4744
    """

    _fn = staticmethod(normalized_mutual_info_score)
    plot_upper_bound: float = 1.0

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def _compute_fn_args(self):
        return (self.average_method,)


class AdjustedMutualInfoScore(NormalizedMutualInfoScore):
    """Adjusted Mutual Info Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import AdjustedMutualInfoScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> m = AdjustedMutualInfoScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        -0.25
    """

    _fn = staticmethod(adjusted_mutual_info_score)
    plot_lower_bound: float = -1.0


class _EmbeddingClusteringMetric(Metric):
    """Base for metrics over (data, labels) embeddings."""

    is_differentiable = True
    full_state_update = True
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("data", default=[], dist_reduce_fx="cat")
        self.add_state("labels", default=[], dist_reduce_fx="cat")

    def update(self, data: Array, labels: Array) -> None:
        self.data.append(data)
        self.labels.append(labels)

    def _compute_fn_args(self):
        return ()

    def compute(self) -> Array:
        return type(self)._fn(dim_zero_cat(self.data), dim_zero_cat(self.labels), *self._compute_fn_args())


class CalinskiHarabaszScore(_EmbeddingClusteringMetric):
    """Calinski Harabasz Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import CalinskiHarabaszScore
        >>> import jax.numpy as jnp
        >>> data = jnp.asarray([[0.0, 0.1], [0.1, 0.0], [4.0, 4.1], [4.1, 4.0], [8.0, 8.1], [8.1, 8.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> m = CalinskiHarabaszScore()
        >>> m.update(data, labels)
        >>> round(float(m.compute()), 4)
        6399.9868
    """

    _fn = staticmethod(calinski_harabasz_score)
    higher_is_better = True


class DaviesBouldinScore(_EmbeddingClusteringMetric):
    """Davies Bouldin Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import DaviesBouldinScore
        >>> import jax.numpy as jnp
        >>> data = jnp.asarray([[0.0, 0.1], [0.1, 0.0], [4.0, 4.1], [4.1, 4.0], [8.0, 8.1], [8.1, 8.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> m = DaviesBouldinScore()
        >>> m.update(data, labels)
        >>> round(float(m.compute()), 4)
        0.025
    """

    _fn = staticmethod(davies_bouldin_score)
    higher_is_better = False


class DunnIndex(_EmbeddingClusteringMetric):
    """Dunn Index (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.clustering import DunnIndex
        >>> import jax.numpy as jnp
        >>> data = jnp.asarray([[0.0, 0.1], [0.1, 0.0], [4.0, 4.1], [4.1, 4.0], [8.0, 8.1], [8.1, 8.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> m = DunnIndex()
        >>> m.update(data, labels)
        >>> round(float(m.compute()), 4)
        79.9997
    """

    _fn = staticmethod(dunn_index)
    higher_is_better = True

    def __init__(self, p: float = 2, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.p = p

    def _compute_fn_args(self):
        return (self.p,)
