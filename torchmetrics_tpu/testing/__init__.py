"""Testing utilities — the fault-injection harness (ISSUE 2).

``torchmetrics_tpu.testing.faults`` provides the chaos primitives the
failure-containment suite (tests/test_fault_containment.py) is built on; they
are public so downstream training stacks can chaos-test their own metric
pipelines the same way.
"""
from torchmetrics_tpu.testing.faults import (  # noqa: F401
    FaultInjected,
    break_sync,
    corrupt_state,
    fail_dispatch,
    hang_sync,
    poison_batch,
    raise_in_compute,
    raise_in_update,
)

__all__ = [
    "FaultInjected",
    "break_sync",
    "corrupt_state",
    "fail_dispatch",
    "hang_sync",
    "poison_batch",
    "raise_in_compute",
    "raise_in_update",
]
