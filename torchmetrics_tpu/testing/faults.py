"""Fault-injection harness for chaos-testing metric pipelines (ISSUE 2).

Production TPU failures are rarely clean exceptions: a bad batch poisons an
accumulator, a dispatch dies after the runtime took ownership of donated
buffers, a multi-host collective hangs because one process fell over, a resume
checkpoint comes back truncated. Each primitive here injects exactly one of
those faults, deterministically, on a single host — so the containment
guarantees (docs/ROBUSTNESS.md) are *asserted*, not assumed:

- :func:`poison_batch` — NaN/Inf-corrupt input arrays.
- :func:`raise_in_update` / :func:`raise_in_compute` — raise at a chosen point
  inside the metric body, optionally *after* state mutation (the half-mutated
  accumulator case).
- :func:`fail_dispatch` — make every executor dispatch raise, optionally after
  the compiled call consumed its donated inputs.
- :func:`hang_sync` / :func:`break_sync` — stall or break the multi-host
  ``process_allgather`` seam (drives ``sync_timeout`` / ``on_sync_failure``).
- :func:`flaky_sync` — fail the sync seam exactly k times then succeed
  (drives ``on_sync_failure="retry"`` backoff, io/retry.py).
- :func:`corrupt_state` — damage a state pytree (shape/dtype/structure/NaN)
  the way a torn checkpoint would (drives ``load_state(validate=...)``).
- :func:`torn_write` — truncate/zero/bit-flip a snapshot FILE the way a
  crash mid-write presents (drives ``restore_state``'s torn-write detection
  and rotating fallback, io/checkpoint.py).
- :func:`corrupt_cache_entry` / :func:`stale_cache_version` — damage or
  version-stale a persisted-executable cache entry (ops/compile_cache.py):
  a poisoned disk cache must degrade to a fresh compile with a warning,
  never crash or change a result.
- :func:`preempt_after` — raise a simulated preemption after the n-th
  COMMITTED update (drives autosave + kill/restore chaos tests).
- :func:`drop_shard` — make a deferred step's compiled dispatches raise an
  attributed ``ShardLossError`` (a device shard's locally-accumulated state
  is gone; drives the ``on_shard_loss`` policies + shard shadow,
  docs/ROBUSTNESS.md "Shard loss").
- :func:`shrink_world` / :func:`grow_world` — simulate a preemption
  rescheduled onto a DIFFERENT slice shape: the checkpoint layer's
  world-topology probe reports ``to`` devices and a matching sub-mesh is
  yielded (drives ``restore_state(topology="strict"|"elastic")`` and the
  ``parallel/reshard.py`` seam).
- :func:`poison_session` / :func:`fail_lane_dispatch` — lane-targeted faults
  against ONE tenant of a laned metric (docs/LANES.md "Failure semantics"):
  corrupt only that session's rows, or raise an attributed
  ``LaneFaultError`` inside the laned update path — the blast-radius
  primitives behind the per-tenant isolation chaos suite.
- :func:`skew_clock` / :func:`late_event` — windowed-state chaos against a
  laned metric's event-time semantics (docs/STREAMING.md): run one lane's
  window clock ahead of the fleet, or deliver a batch stamped ``age``
  windows late — the primitives the watermark admit/drop boundary and the
  skewed-clock read invariants are asserted against.
- :func:`drop_delta` / :func:`duplicate_delta` / :func:`delay_delta` /
  :func:`partition_leaf` — fleet-uplink faults at the ``Uplink.transmit``
  delivery seam (docs/FLEET.md "Failure table"): lose the first n delivery
  attempts from a leaf, deliver each of its deltas twice, hold one delta
  back and inject it late (a genuine reorder at the ledger), or black-hole
  the leaf entirely for a stretch of epochs — the primitives the
  exactly-once convergence property is asserted against.
- :func:`kill_aggregator` — take an aggregator node down (every receive
  fails at the transport level) for the failover/restore chaos suite.

All context managers restore the patched seam on exit, including when the
body raises. They are process-local and NOT thread-safe (they patch module
and class attributes) — use from a single test thread.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Generator, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class FaultInjected(RuntimeError):
    """Default exception raised by the injection primitives — distinct from
    anything the framework raises itself, so tests can assert the *injected*
    fault (and nothing else) escaped."""


class PreemptionInjected(BaseException):
    """Raised by :func:`preempt_after` — a BaseException (like the
    ``SystemExit``/``KeyboardInterrupt`` a real SIGTERM path produces) so
    ordinary ``except Exception`` recovery code cannot accidentally swallow
    the simulated kill."""


# --------------------------------------------------------------------- inputs

def poison_batch(*arrays: Any, mode: str = "nan", frac: float = 0.25, seed: int = 0) -> Tuple[Any, ...]:
    """Corrupt a fraction of every floating-point array's entries with NaN
    (``mode="nan"``) or +/-Inf (``mode="inf"``). Integer arrays (labels) pass
    through untouched. Deterministic in ``seed``.

    >>> import jax.numpy as jnp
    >>> (x,) = poison_batch(jnp.zeros(8), frac=0.5, seed=1)
    >>> int(jnp.isnan(x).sum()) == 4
    True
    """
    if mode not in ("nan", "inf"):
        raise ValueError(f"mode must be 'nan' or 'inf', got {mode!r}")
    rng = np.random.RandomState(seed)
    out = []
    for arr in arrays:
        a = np.array(arr)
        if not np.issubdtype(a.dtype, np.floating):
            out.append(arr)
            continue
        flat = a.reshape(-1)
        k = max(1, int(round(frac * flat.size)))
        idx = rng.choice(flat.size, size=min(k, flat.size), replace=False)
        if mode == "nan":
            flat[idx] = np.nan
        else:
            flat[idx] = np.where(rng.rand(len(idx)) < 0.5, np.inf, -np.inf)
        out.append(jnp.asarray(flat.reshape(a.shape)))
    return tuple(out)


# --------------------------------------------------------------- metric body

@contextmanager
def raise_in_update(
    metric: Any, exc: Optional[BaseException] = None, after_mutation: bool = True
) -> Generator[None, None, None]:
    """Make ``metric``'s update body raise.

    With ``after_mutation=True`` (default) the REAL update body runs first —
    the live state is already mutated when the exception fires, which is
    exactly the half-applied-transition case the transactional wrapper must
    roll back. ``after_mutation=False`` raises before touching anything.

    The patch targets ``metric._update_fn``, the seam every path shares
    (eager body, ``functional_update``, executor builders). Note for
    executor-enabled metrics: an executable compiled BEFORE entering this
    context has the original body baked in — inject on a cold instance (so
    the fault traces in) or use :func:`fail_dispatch` for warm ones.
    """
    orig = metric._update_fn
    error = exc if exc is not None else FaultInjected("injected update failure")

    def failing(*args: Any, **kwargs: Any) -> None:
        if after_mutation:
            orig(*args, **kwargs)
        raise error

    object.__setattr__(metric, "_update_fn", failing)
    try:
        yield
    finally:
        object.__setattr__(metric, "_update_fn", orig)


@contextmanager
def raise_in_compute(metric: Any, exc: Optional[BaseException] = None) -> Generator[None, None, None]:
    """Make ``metric``'s compute body raise (patches ``metric._compute_fn``,
    shared by the eager wrapper and ``functional_compute``)."""
    orig = metric._compute_fn
    error = exc if exc is not None else FaultInjected("injected compute failure")

    def failing(*args: Any, **kwargs: Any) -> Any:
        raise error

    object.__setattr__(metric, "_compute_fn", failing)
    try:
        yield
    finally:
        object.__setattr__(metric, "_compute_fn", orig)


# -------------------------------------------------------------------- lanes

@contextmanager
def poison_session(
    laned: Any, session_id: Any, mode: str = "nan", frac: float = 0.25, seed: int = 0
) -> Generator[None, None, None]:
    """Corrupt ONLY ``session_id``'s rows in every ``update_sessions`` round
    on ``laned`` (a ``LanedMetric`` or ``LanedCollection``) — the one-bad-
    tenant scenario the lane isolation property is asserted against: every
    OTHER session's per-lane ``compute()`` must stay bit-exact vs a fault-free
    run. Composes with the other chaos context managers; ``mode``/``frac``/
    ``seed`` are :func:`poison_batch`'s."""
    orig = laned.update_sessions

    def poisoned(items: Any, **kwargs: Any) -> int:
        items = list(items.items()) if isinstance(items, dict) else list(items)
        out = []
        for sid, batch in items:
            if sid == session_id:
                was_tuple = isinstance(batch, tuple)
                leaves = batch if was_tuple else (batch,)
                leaves = poison_batch(*leaves, mode=mode, frac=frac, seed=seed)
                batch = leaves if was_tuple else leaves[0]
            out.append((sid, batch))
        return orig(out, **kwargs)

    object.__setattr__(laned, "update_sessions", poisoned)
    try:
        yield
    finally:
        if laned.__dict__.get("update_sessions") is poisoned:
            del laned.__dict__["update_sessions"]


@contextmanager
def fail_lane_dispatch(
    laned: Any, session_id: Any, fail_n: Optional[int] = None, exc: Optional[BaseException] = None
) -> Generator[None, None, None]:
    """Raise an attributed ``LaneFaultError(session_id)`` from inside the
    laned update path whenever a dispatched round contains that session's
    lane — AFTER the real update ran (the committed-then-faulted worst case,
    like ``raise_in_update(after_mutation=True)``). The router's containment
    must roll the touched lanes back and re-dispatch the round without the
    culprit, so the other lanes sharing the dispatch still get their step.
    ``fail_n=k`` faults only the first k hits; ``None`` faults every one."""
    from torchmetrics_tpu.utils.exceptions import LaneFaultError

    targets = list(laned._members.values()) if hasattr(laned, "_members") else [laned]
    orig_update = targets[0].update if len(targets) == 1 else None
    orig_coll_update = laned.collection.update if hasattr(laned, "collection") else None
    remaining = {"n": fail_n}

    def should_fail(lane_ids: Any) -> bool:
        lane = laned.sessions.get(session_id)
        if lane is None or lane not in np.asarray(lane_ids).reshape(-1):
            return False
        if remaining["n"] is not None:
            if remaining["n"] <= 0:
                return False
            remaining["n"] -= 1
        return True

    error = exc

    def make_failing(orig: Any) -> Any:
        def failing(lane_ids: Any, *args: Any, **kwargs: Any) -> Any:
            hit = should_fail(lane_ids)
            out = orig(lane_ids, *args, **kwargs)
            if hit:
                raise error if error is not None else LaneFaultError(
                    f"injected lane dispatch failure for session {session_id!r}",
                    session_id=session_id,
                    where="dispatch",
                )
            return out

        return failing

    if orig_coll_update is not None:
        patched_target, attr = laned.collection, "update"
        object.__setattr__(patched_target, attr, make_failing(orig_coll_update))
    else:
        patched_target, attr = targets[0], "update"
        object.__setattr__(patched_target, attr, make_failing(orig_update))
    try:
        yield
    finally:
        object.__setattr__(
            patched_target, attr, orig_coll_update if orig_coll_update is not None else orig_update
        )


# ------------------------------------------------------------- window clocks

def skew_clock(laned: Any, lane: int, by: int = 1) -> int:
    """Run ONE lane's window clock ``by`` windows AHEAD of the fleet — the
    per-tenant event-time drift scenario (docs/STREAMING.md "Clock skew"):
    a tenant whose stream runs fast closes its windows early while every
    other lane stays put. The skew is real ring state (the lane's retiring
    slots are identity-reset), so it is deliberately NOT undone — compose
    with the other chaos managers around the traffic you drive afterwards.
    Returns the lane's new clock."""
    laned.advance_lane_windows(int(lane), int(by))
    return int(laned._window_clocks()[int(lane)])


def late_event(laned: Any, session_id: Any, batch: Any, age: int = 1) -> int:
    """Deliver ``batch`` for ``session_id`` stamped ``age`` windows behind
    the session's CURRENT lane clock — the watermark chaos primitive. Within
    the lateness bound the event must land in its still-open ring slot;
    beyond it the watermark must drop it with a ``window_late_drop``
    breadcrumb and count ``windows.dropped_late``. Returns the dispatch
    count (0 == dropped), so a test asserts either outcome directly."""
    lane = laned._router_admit(session_id)
    clock = int(laned._window_clocks()[lane])
    k = clock - int(age)
    if k < 0:
        raise ValueError(
            f"cannot inject an event {age} windows late: lane clock is only {clock}"
        )
    return laned.update_sessions({session_id: batch}, window=k)


# ----------------------------------------------------------------- executor

@contextmanager
def fail_dispatch(
    exc: Optional[BaseException] = None, consume: bool = True, fail_n: Optional[int] = None
) -> Generator[None, None, None]:
    """Make donated-state executor dispatches raise.

    With ``consume=True`` (default) the real compiled function is invoked
    first — donated input buffers are genuinely consumed before the failure,
    the worst case the executor's host-side recovery reference exists for.
    ``fail_n=k`` fails only the first k dispatches then passes calls through
    untouched (drives the warm-dispatch retry path, io/retry.py); ``None``
    (default) fails every dispatch. Patches ``_ExecutorBase._get_fn``
    class-wide; affects all metrics until exit.
    """
    from torchmetrics_tpu.ops import executor as executor_mod

    orig = executor_mod._ExecutorBase._get_fn
    error = exc if exc is not None else FaultInjected("injected dispatch failure")
    remaining = {"n": fail_n}

    def patched(self: Any, key: Any, builder: Any, *get_args: Any, **get_kwargs: Any):
        fn, fresh = orig(self, key, builder, *get_args, **get_kwargs)
        if fn is None:  # background-compile miss: nothing dispatched to fail
            return fn, fresh

        def failing(*args: Any, **kwargs: Any) -> Any:
            if remaining["n"] is not None and remaining["n"] <= 0:
                return fn(*args, **kwargs)
            if remaining["n"] is not None:
                remaining["n"] -= 1
            if consume:
                fn(*args, **kwargs)
            raise error

        return failing, fresh

    executor_mod._ExecutorBase._get_fn = patched
    try:
        yield
    finally:
        executor_mod._ExecutorBase._get_fn = orig


# ---------------------------------------------------------- elastic topology

@contextmanager
def drop_shard(
    step: Any, shard: int = 0, fail_n: Optional[int] = 1, exc: Optional[BaseException] = None
) -> Generator[None, None, None]:
    """Make ``step``'s (a ``DeferredCollectionStep``) compiled dispatches
    raise an attributed ``ShardLossError`` — the deferred-mode failure where
    a device dies and its locally-accumulated shard of state dies with it.

    ``fail_n=k`` (default 1) faults only the first k dispatches inside the
    context, then passes calls through — the shape of a shard lost once and
    recovered (``on_shard_loss="restore"`` reinstalls the host shadow and the
    re-dispatch succeeds); ``None`` faults every dispatch (a world that stays
    broken: even ``"restore"`` recovery re-raises). Composes with
    :func:`preempt_after` / :func:`torn_write` / :func:`shrink_world` for the
    kill-restore-resize chaos suite.
    """
    from torchmetrics_tpu.utils.exceptions import ShardLossError

    orig = step._get
    remaining = {"n": fail_n}

    def patched(key: Any, builder: Any) -> Any:
        fn = orig(key, builder)

        def failing(*args: Any, **kwargs: Any) -> Any:
            if remaining["n"] is not None and remaining["n"] <= 0:
                return fn(*args, **kwargs)
            if remaining["n"] is not None:
                remaining["n"] -= 1
            raise exc if exc is not None else ShardLossError(
                f"injected loss of shard {shard} (device died mid-epoch)", shard=shard
            )

        return failing

    step._get = patched
    try:
        yield
    finally:
        if step.__dict__.get("_get") is patched:
            del step.__dict__["_get"]


@contextmanager
def _resized_world(to: int) -> Generator[Any, None, None]:
    """Shared body of :func:`shrink_world`/:func:`grow_world`: patch the
    checkpoint layer's world-topology probe to report ``to`` devices and
    yield a Mesh over the first ``to`` local devices."""
    import jax
    from jax.sharding import Mesh

    from torchmetrics_tpu.io import checkpoint as checkpoint_mod

    devices = jax.devices()
    if not 1 <= to <= len(devices):
        raise ValueError(
            f"resized world must fit the local device pool (1..{len(devices)}), got {to}"
        )
    orig = checkpoint_mod._world_topology

    def patched() -> Dict[str, Any]:
        out = dict(orig())
        out["device_count"] = int(to)
        return out

    checkpoint_mod._world_topology = patched
    try:
        yield Mesh(np.array(devices[:to]), ("batch",))
    finally:
        checkpoint_mod._world_topology = orig


@contextmanager
def shrink_world(to: int) -> Generator[Any, None, None]:
    """Simulate the job being rescheduled onto a SMALLER slice: snapshots
    saved (and restores attempted) inside the context see a world of ``to``
    devices, and the yielded ``Mesh`` spans exactly those devices — so a
    checkpoint saved on the full mesh hits ``restore_state``'s topology gate
    (``TopologyMismatchError`` under ``"strict"``, fold/reshard under
    ``"elastic"``). Composes with :func:`preempt_after` (kill, then restore
    into a shrunken world) and :func:`torn_write` (rotation fallback across
    a topology change)."""
    with _resized_world(to) as mesh:
        yield mesh


@contextmanager
def grow_world(to: int) -> Generator[Any, None, None]:
    """Simulate rescheduling onto a BIGGER slice (bounded by the local
    device pool — under the 8-virtual-device test harness, up to 8). Same
    seam as :func:`shrink_world`; the direction only matters to the test's
    semantics."""
    with _resized_world(to) as mesh:
        yield mesh


# --------------------------------------------------------------------- sync

@contextmanager
def pause_async_reads(max_s: float = 30.0) -> Generator[threading.Event, None, None]:
    """Park the async read pipeline's worker (ops/async_read.py) on a barrier
    job, so every read submitted INSIDE the context stays in flight until the
    context exits (or ``max_s`` elapses — a safety valve so a crashed test
    cannot wedge the worker for the rest of the suite). Yields the release
    event; set it early to unpark before the context ends.

    Composes with the other managers: ``break_sync`` + ``pause_async_reads``
    lets a test assert policy handling of a failure that is *guaranteed* to
    happen while the future is still pending; a preemption flush with a read
    in flight is ``pause_async_reads`` + ``install_preemption_handler``.
    """
    from torchmetrics_tpu.ops.async_read import get_pipeline

    release = threading.Event()

    def barrier() -> None:
        release.wait(max_s)

    get_pipeline().submit(barrier, owner="faults.pause_async_reads")
    try:
        yield release
    finally:
        release.set()


@contextmanager
def hang_sync(seconds: float = 30.0) -> Generator[None, None, None]:
    """Stall the multi-host ``process_allgather`` seam by ``seconds`` before
    letting it proceed — a metric with ``sync_timeout < seconds`` sees a
    :class:`~torchmetrics_tpu.utils.exceptions.SyncTimeoutError`; one without
    a bound blocks, exactly like a real dead-peer rendezvous."""
    from torchmetrics_tpu.parallel import sync as sync_mod

    orig = sync_mod._process_allgather

    def hanging(value: Any) -> Any:
        time.sleep(seconds)
        return orig(value)

    sync_mod._process_allgather = hanging
    try:
        yield
    finally:
        sync_mod._process_allgather = orig


@contextmanager
def break_sync(exc: Optional[BaseException] = None) -> Generator[None, None, None]:
    """Make the multi-host ``process_allgather`` seam raise immediately (a
    collective aborted by the runtime rather than hung)."""
    from torchmetrics_tpu.parallel import sync as sync_mod

    orig = sync_mod._process_allgather
    error = exc if exc is not None else FaultInjected("injected sync failure")

    def failing(value: Any) -> Any:
        raise error

    sync_mod._process_allgather = failing
    try:
        yield
    finally:
        sync_mod._process_allgather = orig


@contextmanager
def flaky_sync(
    fail_n: int = 1, exc: Optional[BaseException] = None
) -> Generator[Dict[str, int], None, None]:
    """Make the multi-host ``process_allgather`` seam fail exactly ``fail_n``
    times, then succeed — the transient-abort signature (a peer restarting
    mid-rendezvous) that ``on_sync_failure="retry"`` exists for. Yields a
    counters dict (``attempts``/``failures``) so tests can assert the retry
    schedule actually exercised the seam."""
    from torchmetrics_tpu.parallel import sync as sync_mod

    orig = sync_mod._process_allgather
    error = exc if exc is not None else FaultInjected("injected transient sync failure")
    counters = {"attempts": 0, "failures": 0}

    def sometimes_failing(value: Any) -> Any:
        counters["attempts"] += 1
        if counters["failures"] < fail_n:
            counters["failures"] += 1
            raise error
        return orig(value)

    sync_mod._process_allgather = sometimes_failing
    try:
        yield counters
    finally:
        sync_mod._process_allgather = orig


# -------------------------------------------------------------- checkpoints

def corrupt_state(
    state: Dict[str, Any], mode: str = "nan", field: Optional[str] = None, seed: int = 0
) -> Dict[str, Any]:
    """A damaged copy of a state pytree, the way a torn/bit-flipped resume
    checkpoint presents. The input is never modified.

    Modes (``field`` picks the victim; default: first eligible array field):

    - ``"shape"``   — the field's array gains a bogus leading dim.
    - ``"dtype"``   — the field's array is cast float<->int.
    - ``"structure"`` — the field's key is deleted outright.
    - ``"nan"``     — a random entry of a float field becomes NaN.
    """
    if mode not in ("shape", "dtype", "structure", "nan"):
        raise ValueError(f"mode must be one of shape/dtype/structure/nan, got {mode!r}")
    out = {k: (list(v) if isinstance(v, list) else v) for k, v in state.items()}
    candidates = [
        k for k, v in state.items()
        if not isinstance(v, (list, tuple)) and hasattr(v, "dtype") and k != "_update_count"
    ]
    if mode == "nan":
        candidates = [k for k in candidates if np.issubdtype(np.asarray(state[k]).dtype, np.floating)]
    if field is not None:
        if field not in state:
            raise KeyError(f"field {field!r} not in state")
        candidates = [field]
    if not candidates:
        raise ValueError(f"state has no array field eligible for mode {mode!r}")
    victim = candidates[0]
    value = jnp.asarray(state[victim])
    if mode == "shape":
        out[victim] = jnp.stack([value, value])
    elif mode == "dtype":
        if jnp.issubdtype(value.dtype, jnp.floating):
            out[victim] = value.astype(jnp.int32)
        else:
            out[victim] = value.astype(jnp.float32)
    elif mode == "structure":
        del out[victim]
    else:  # nan
        flat = np.array(value).reshape(-1)
        flat[np.random.RandomState(seed).randint(0, flat.size)] = np.nan
        out[victim] = jnp.asarray(flat.reshape(value.shape))
    return out


def _flip_bits_host(arr: np.ndarray, n_bits: int, seed: int) -> Tuple[np.ndarray, list]:
    """Flip ``n_bits`` distinct random bits of ``arr``'s raw bytes; returns
    the damaged copy and the flat bit positions hit."""
    out = np.array(arr)
    raw = out.reshape(-1).view(np.uint8)
    if raw.size == 0:
        raise ValueError("cannot flip bits of an empty array")
    rng = np.random.RandomState(seed)
    total = raw.size * 8
    positions = rng.choice(total, size=min(int(n_bits), total), replace=False)
    for pos in positions:
        raw[pos // 8] ^= np.uint8(1 << (pos % 8))
    return out, [int(p) for p in positions]


def flip_state_bits(
    target: Any, field: Optional[str] = None, n_bits: int = 1, seed: int = 0
) -> Any:
    """Silent data corruption: flip ``n_bits`` random bits of one state
    leaf's raw bytes — the mercurial-core / DMA-corruption signature the
    integrity layer (torchmetrics_tpu/integrity.py) exists to catch. No
    shape, dtype, or NaN tell: only the bits change, so every pre-integrity
    validator passes. Deterministic in ``seed``.

    ``target`` is either a live ``Metric`` (its ``_state`` is corrupted IN
    PLACE; returns an info dict with the victim ``field`` and flat ``bits``
    hit) or a plain state pytree, e.g. the deferred loop's carried states
    (never modified; returns ``(flipped_copy, info)`` — swap the copy in).
    ``field`` picks the victim leaf (Metric targets; default first array
    field); pytree targets flip the first array leaf found.
    """
    import jax as _jax

    if hasattr(target, "_state") and isinstance(getattr(target, "_state"), dict):
        state = target._state
        candidates = [
            k for k, v in state.items()
            if not isinstance(v, (list, tuple)) and hasattr(v, "dtype") and k != "_update_count"
        ]
        if field is not None:
            if field not in state:
                raise KeyError(f"field {field!r} not in state")
            candidates = [field]
        if not candidates:
            raise ValueError("metric state has no array field to corrupt")
        victim = candidates[0]
        value = state[victim]
        flipped, bits = _flip_bits_host(np.array(value), n_bits, seed)
        new_leaf = jnp.asarray(flipped)
        try:  # keep the victim on its original placement (sharded deferred leaves)
            new_leaf = _jax.device_put(new_leaf, value.sharding)
        except (AttributeError, ValueError):
            pass
        object.__setattr__(target, "_state", {**state, victim: new_leaf})
        target.__dict__["_computed"] = None  # a cached read would mask the flip
        return {"field": victim, "bits": bits}

    leaves, treedef = _jax.tree_util.tree_flatten(target)
    idx = next(
        (i for i, leaf in enumerate(leaves) if hasattr(leaf, "dtype") and hasattr(leaf, "shape")),
        None,
    )
    if idx is None:
        raise ValueError("pytree has no array leaf to corrupt")
    value = leaves[idx]
    flipped, bits = _flip_bits_host(np.array(value), n_bits, seed)
    new_leaf = jnp.asarray(flipped)
    try:
        new_leaf = _jax.device_put(new_leaf, value.sharding)
    except (AttributeError, ValueError):
        pass
    leaves[idx] = new_leaf
    return _jax.tree_util.tree_unflatten(treedef, leaves), {"leaf_index": idx, "bits": bits}


def skew_replica(states: Any, shard: int = 0, n_bits: int = 1, seed: int = 0) -> Tuple[Any, Dict[str, Any]]:
    """Replica drift: flip ``n_bits`` bits in exactly ONE shard row of the
    first stacked array leaf of ``states`` (a deferred loop's carried pytree)
    — every other replica keeps the true bits, the way a single drifting
    device presents. The per-shard fingerprint audit
    (``DeferredCollectionStep.attach_integrity``) must name this shard.
    Returns ``(skewed_copy, info)``; the input is never modified."""
    import jax as _jax

    leaves, treedef = _jax.tree_util.tree_flatten(states)
    idx = next(
        (
            i for i, leaf in enumerate(leaves)
            if hasattr(leaf, "dtype") and getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] > shard
        ),
        None,
    )
    if idx is None:
        raise ValueError(f"states has no stacked array leaf with a shard {shard}")
    value = leaves[idx]
    host = np.array(value)
    row, bits = _flip_bits_host(host[shard], n_bits, seed)
    host[shard] = row
    new_leaf = jnp.asarray(host)
    try:
        new_leaf = _jax.device_put(new_leaf, value.sharding)
    except (AttributeError, ValueError):
        pass
    leaves[idx] = new_leaf
    return (
        _jax.tree_util.tree_unflatten(treedef, leaves),
        {"leaf_index": idx, "shard": int(shard), "bits": bits},
    )


@contextmanager
def corrupt_delta_payload(leaf: Any, n: int = 1, seed: int = 0) -> Generator[Dict[str, int], None, None]:
    """Corrupt the first ``n`` of ``leaf``'s deltas IN FLIGHT at the
    ``Uplink.transmit`` seam: a bit flips in the payload after the exporter
    stamped its ship-time checksum (fleet/delta.py ``payload_checksum``), the
    way a relay/serialization fault presents. The receiving ledger must hash-
    mismatch, DROP without merging, quarantine the leaf, and heal through the
    requested full resync — converging bit-exact with the fault-free run.
    The sender's outbox copy is never touched (the corruption is a transport
    event, not a source event). Yields counters (``corrupted``)."""
    import copy
    import dataclasses

    from torchmetrics_tpu.fleet import transport as transport_mod

    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    orig = transport_mod.Uplink.transmit
    counters = {"corrupted": 0}
    rng = np.random.RandomState(seed)

    def damaged(payload: Any) -> Any:
        out = copy.deepcopy(payload)

        def walk(value: Any) -> bool:
            if isinstance(value, dict):
                return any(walk(v) for v in value.values())
            if isinstance(value, (list, tuple)):
                return any(walk(v) for v in value)
            if isinstance(value, np.ndarray) and value.size:
                raw = value.reshape(-1).view(np.uint8)
                pos = int(rng.randint(0, raw.size * 8))
                raw[pos // 8] ^= np.uint8(1 << (pos % 8))
                return True
            return False

        if not walk(out):
            raise ValueError(f"delta payload for {leaf!r} has no array to corrupt")
        return out

    def patched(self: Any, node_id: str, delta: Any) -> Any:
        if delta.leaf == leaf and counters["corrupted"] < n:
            counters["corrupted"] += 1
            delta = dataclasses.replace(delta, payload=damaged(delta.payload))
        return orig(self, node_id, delta)

    transport_mod.Uplink.transmit = patched
    try:
        yield counters
    finally:
        transport_mod.Uplink.transmit = orig


def torn_write(path: Any, mode: str = "truncate", frac: float = 0.5, seed: int = 0) -> None:
    """Damage a snapshot FILE in place, the way real storage failures present.

    Modes:

    - ``"truncate"`` (default) — keep only the first ``frac`` of the bytes: a
      crash/preemption mid-write (the torn write io/checkpoint.py's atomic
      rename exists to prevent — this primitive fakes the case where it
      somehow happened anyway, e.g. a copied/rsynced partial file).
    - ``"zero"`` — overwrite the last ``1-frac`` of the bytes with zeros, same
      length: a storage layer that acknowledged before persisting.
    - ``"flip"`` — flip one random byte's bits: silent media bit rot (caught
      by the per-leaf sha256, not by length/structure checks).

    Deterministic in ``seed``. The damaged file must be *detected* by
    ``restore_state`` (typed ``CheckpointCorruptionError``), never installed.
    """
    import os

    path = os.fspath(path)
    if mode not in ("truncate", "zero", "flip"):
        raise ValueError(f"mode must be truncate/zero/flip, got {mode!r}")
    if not 0 <= frac < 1:
        raise ValueError(f"frac must be in [0, 1), got {frac}")
    with open(path, "rb") as fh:
        data = fh.read()
    if not data:
        raise ValueError(f"{path} is empty; nothing to tear")
    if mode == "truncate":
        damaged = data[: max(1, int(len(data) * frac))]
    elif mode == "zero":
        cut = max(1, int(len(data) * frac))
        damaged = data[:cut] + b"\x00" * (len(data) - cut)
    else:  # flip
        idx = np.random.RandomState(seed).randint(0, len(data))
        damaged = data[:idx] + bytes([data[idx] ^ 0xFF]) + data[idx + 1:]
    # deliberately NON-atomic: the point is to leave the damaged bytes under
    # the real name, as the failure mode would
    with open(path, "wb") as fh:
        fh.write(damaged)


def _cache_entry_paths(cache_dir: Optional[str]) -> list:
    """Persisted-executable entries under ``cache_dir`` (default: the
    resolved ``TORCHMETRICS_TPU_CACHE_DIR``), newest first."""
    import os

    from torchmetrics_tpu.ops import compile_cache

    directory = cache_dir if cache_dir is not None else compile_cache.cache_dir()
    if directory is None:
        raise ValueError("no cache directory resolved (compile-ahead disabled?)")
    store = os.path.join(directory, "executables")
    try:
        names = [n for n in os.listdir(store) if n.endswith(compile_cache.ENTRY_SUFFIX)]
    except FileNotFoundError:
        raise ValueError(f"no executable store at {store}") from None
    paths = [os.path.join(store, n) for n in names]
    return sorted(paths, key=lambda p: os.path.getmtime(p), reverse=True)


def corrupt_cache_entry(
    cache_dir: Optional[str] = None, mode: str = "flip", which: str = "newest", frac: float = 0.5, seed: int = 0
) -> list:
    """Damage persisted-executable cache entries in place (ops/compile_cache.py).

    ``mode`` is :func:`torn_write`'s (``truncate``/``zero``/``flip``) plus
    ``"garbage"`` — replace the whole file with bytes that are not even a
    valid container. ``which`` picks victims: ``"newest"``, ``"oldest"`` or
    ``"all"``. Returns the damaged paths. The next executor miss touching a
    damaged entry must WARN, delete it, and recompile fresh — identical
    results, no crash (the chaos contract of docs/EXECUTOR.md).
    """
    paths = _cache_entry_paths(cache_dir)
    victims = paths if which == "all" else [paths[0] if which == "newest" else paths[-1]]
    for path in victims:
        if mode == "garbage":
            with open(path, "wb") as fh:
                fh.write(b"\x00garbage-not-a-cache-entry" * 16)
        else:
            torn_write(path, mode=mode, frac=frac, seed=seed)
    return victims


def stale_cache_version(cache_dir: Optional[str] = None, which: str = "newest") -> list:
    """Rewrite entry headers with a STALE toolchain fingerprint, exactly as a
    binary from an older jax/library version would have left them (the
    payload stays intact and checksummed — only the version line lies).
    ``load_executable_blob`` must refuse such an entry (skip + warn + delete),
    never deserialize an executable built by different code. Returns paths.
    """
    import json

    from torchmetrics_tpu.ops.compile_cache import ENTRY_MAGIC

    paths = _cache_entry_paths(cache_dir)
    victims = paths if which == "all" else [paths[0] if which == "newest" else paths[-1]]
    for path in victims:
        with open(path, "rb") as fh:
            data = fh.read()
        hlen = int.from_bytes(data[len(ENTRY_MAGIC):len(ENTRY_MAGIC) + 8], "little")
        h_start = len(ENTRY_MAGIC) + 8
        header = json.loads(data[h_start:h_start + hlen].decode())
        header["toolchain"] = "tm_tpu=0.0.0|jax=0.0.0|jaxlib=0.0.0|executor=stale|compile_cache=stale"
        new_header = json.dumps(header, sort_keys=True).encode()
        # deliberately NON-atomic (plain rewrite): simulating a foreign writer
        with open(path, "wb") as fh:
            fh.write(ENTRY_MAGIC + len(new_header).to_bytes(8, "little") + new_header + data[h_start + hlen:])
    return victims


@contextmanager
def preempt_after(
    metric: Any, n_updates: int, exc: Optional[BaseException] = None
) -> Generator[None, None, None]:
    """Simulate a preemption (SIGTERM) arriving after the ``n_updates``-th
    COMMITTED top-level update/forward on ``metric`` (a ``Metric`` or
    ``MetricCollection``).

    The raise happens from the post-commit observer seam — state is fully
    consistent (exactly n updates applied), mirroring a signal delivered
    between steps. Raises :class:`PreemptionInjected` (a BaseException) so
    recovery code catching ``Exception`` cannot swallow it. Because the
    observer fires AFTER any attached Autosaver registered earlier... order
    note: observers run in attach order, so attach the Autosaver first if the
    final update should still be autosaved before the kill.
    """
    if n_updates < 1:
        raise ValueError(f"n_updates must be >= 1, got {n_updates}")
    error = exc if exc is not None else PreemptionInjected(
        f"injected preemption after update {n_updates}"
    )
    seen = {"n": 0}

    def observer(_obj: Any) -> None:
        seen["n"] += 1
        if seen["n"] == n_updates:
            raise error

    detach = metric.add_update_observer(observer)
    try:
        yield
    finally:
        detach()


# -------------------------------------------------------------- fleet uplink

@contextmanager
def drop_delta(leaf: Any, n: int = 1) -> Generator[Dict[str, int], None, None]:
    """Lose the first ``n`` delivery ATTEMPTS of ``leaf``'s deltas at the
    ``Uplink.transmit`` seam (each retry consumes one — ``n`` larger than the
    retry budget makes a whole ``send`` fail and the outbox retain). The
    exactly-once ledger plus outbox re-ship must make the eventual delivery
    converge bit-exact. Yields a counters dict (``dropped``)."""
    from torchmetrics_tpu.fleet import transport as transport_mod

    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    orig = transport_mod.Uplink.transmit
    counters = {"dropped": 0}

    def patched(self: Any, node_id: str, delta: Any) -> Any:
        if delta.leaf == leaf and counters["dropped"] < n:
            counters["dropped"] += 1
            raise ConnectionError(f"injected drop of {leaf!r} epoch {delta.epoch}")
        return orig(self, node_id, delta)

    transport_mod.Uplink.transmit = patched
    try:
        yield counters
    finally:
        transport_mod.Uplink.transmit = orig


@contextmanager
def duplicate_delta(leaf: Any) -> Generator[Dict[str, int], None, None]:
    """Deliver every one of ``leaf``'s deltas TWICE (the at-least-once
    transport reality: an ack lost on the way back causes a re-send of an
    already-applied epoch). The ledger must drop the duplicate idempotently —
    same global value, ``duplicates`` stat incremented. Yields counters
    (``duplicated``)."""
    from torchmetrics_tpu.fleet import transport as transport_mod

    orig = transport_mod.Uplink.transmit
    counters = {"duplicated": 0}

    def patched(self: Any, node_id: str, delta: Any) -> Any:
        ack = orig(self, node_id, delta)
        if delta.leaf == leaf:
            counters["duplicated"] += 1
            orig(self, node_id, delta)  # second delivery; its ack is discarded
        return ack

    transport_mod.Uplink.transmit = patched
    try:
        yield counters
    finally:
        transport_mod.Uplink.transmit = orig


@contextmanager
def delay_delta(leaf: Any, epochs: int = 2) -> Generator[Dict[str, Any], None, None]:
    """Hold ``leaf``'s NEXT delta back and inject it only after ``epochs``
    later deliveries from that leaf have gone through — a genuine reorder at
    the ledger (the held epoch arrives after its successors, which must sit
    in the pending buffer until the gap fills). The hold answers with a
    synthetic ack (``durable_epoch=0`` so the outbox keeps everything) —
    a transport that accepted the bytes but sat on them. Yields counters
    (``held_epoch``, ``delivered_late``)."""
    from torchmetrics_tpu.fleet import transport as transport_mod

    if epochs < 1:
        raise ValueError(f"epochs must be >= 1, got {epochs}")
    orig = transport_mod.Uplink.transmit
    held: Dict[str, Any] = {"delta": None, "node": None, "later": 0}
    counters: Dict[str, Any] = {"held_epoch": None, "delivered_late": False}

    def synthetic(node_id: str, delta: Any) -> Dict[str, Any]:
        return {
            "leaf": delta.leaf,
            "applied_epoch": delta.epoch,
            "durable_epoch": 0,
            "needs_full": False,
            "node": node_id,
        }

    def patched(self: Any, node_id: str, delta: Any) -> Any:
        if delta.leaf != leaf or counters["delivered_late"]:
            return orig(self, node_id, delta)
        if held["delta"] is None:
            held["delta"], held["node"] = delta, node_id
            counters["held_epoch"] = delta.epoch
            return synthetic(node_id, delta)
        if delta.epoch == held["delta"].epoch:
            return synthetic(node_id, delta)  # re-ship of the held epoch: keep holding
        ack = orig(self, node_id, delta)
        held["later"] += 1
        if held["later"] >= epochs:
            orig(self, held["node"], held["delta"])  # the late, out-of-order arrival
            counters["delivered_late"] = True
        return ack

    transport_mod.Uplink.transmit = patched
    try:
        yield counters
    finally:
        transport_mod.Uplink.transmit = orig


@contextmanager
def partition_leaf(leaf: Any, epochs: int = 3) -> Generator[Dict[str, Any], None, None]:
    """Black-hole every delivery from ``leaf`` until ``epochs`` DISTINCT
    epochs have attempted the uplink — the network-partition signature: the
    leaf keeps exporting into its outbox (possibly tripping its breaker),
    then rejoins and re-ships the backlog in order. Watermark-sized
    partitions must converge by replay; longer ones via the quarantine →
    ``needs_full`` resync path. Yields counters (``dropped_epochs``)."""
    from torchmetrics_tpu.fleet import transport as transport_mod

    if epochs < 1:
        raise ValueError(f"epochs must be >= 1, got {epochs}")
    orig = transport_mod.Uplink.transmit
    seen: set = set()
    counters: Dict[str, Any] = {"dropped_epochs": seen}

    def patched(self: Any, node_id: str, delta: Any) -> Any:
        if delta.leaf == leaf and len(seen) < epochs:
            seen.add(delta.epoch)
            raise ConnectionError(f"injected partition of {leaf!r} (epoch {delta.epoch})")
        return orig(self, node_id, delta)

    transport_mod.Uplink.transmit = patched
    try:
        yield counters
    finally:
        transport_mod.Uplink.transmit = orig


@contextmanager
def kill_aggregator(aggregator: Any) -> Generator[None, None, None]:
    """Take an aggregator node down for the duration of the context: every
    ``receive`` raises ``ConnectionError`` (the transport-level failure the
    uplink retries, breakers on, and outboxes absorb). Revives on exit —
    pair with ``Aggregator.restore`` / ``Fleet.failover`` INSIDE the context
    to drive the successor path instead."""
    aggregator.kill()
    try:
        yield
    finally:
        aggregator.revive()
