"""MeanAveragePrecision — COCO-style mAP, TPU-native.

Spec: reference detection/_mean_ap.py (the pure-tensor COCO mAP with 101-point
interpolation; the reference's public class delegates to pycocotools C code,
detection/mean_ap.py:50-73, which cannot run on device).

Redesign for XLA:
- The reference evaluates each (image, class, area) with Python loops and a
  per-detection greedy match loop (_mean_ap.py:522-650). Here every
  (image, class) pair is padded into one ``(E, Dmax, Gmax)`` grid; the IoU
  matrix is ONE batched op and greedy matching is a single ``lax.scan`` over
  detection rank, vectorized over all pairs, IoU thresholds and area ranges.
- The variable-length 101-point PR interpolation runs on host numpy (cheap,
  O(total_dets log) per class) — the device does the O(E*T*D*G) work.

Host/device placement: where the jitted matcher executes follows jax's
default device. At small scales (tens of images x ~12 dets, the typical eval
batch and bench config 4) the workload is dispatch-latency-bound and pinning
to host CPU wins (``with jax.default_device(jax.devices("cpu")[0])``); as
E*T*Dmax*Gmax grows, the batched IoU + scan matcher amortizes dispatch and
the accelerator wins. The crossover is measured by bench config 4's
``value_on_device``/``device_vs_host_ratio`` rows on real hardware; both
placements produce identical results, so callers choose by scale alone.

Divergence from the legacy spec: ``iscrowd`` ground truths are supported —
crowd ground truths never count toward recall, and detections overlapping a
crowd above the IoU threshold are ignored rather than counted as false
positives (COCO intent); the legacy pure-torch path ignores the flag entirely.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.functional.detection.iou import _inter_union, box_area, box_convert
from torchmetrics_tpu.metric import Metric


@lru_cache(maxsize=8)
def _matching_kernel(num_thresholds: int):
    """Build the jitted greedy matcher for a given threshold count.

    Returns f(ious (E,D,G), crowd_over (E,D,G), gt_ignore (A,E,G), gt_crowd (E,G),
    det_valid (E,D), thresholds (T,)) -> (det_matches, det_crowd) both (A,E,T,D)
    bool: whether each detection matched a non-ignored ground truth at each IoU
    threshold per area range, and whether an otherwise-unmatched detection
    overlaps a crowd ground truth above threshold (such detections are ignored,
    COCO intent). ``crowd_over`` is the COCO crowd overlap — intersection over
    *detection* area, not symmetric IoU — so a small detection inside a large
    crowd region is still absorbed. Greedy in detection rank (detections
    pre-sorted by score), best-IoU ground truth first — reference
    _mean_ap.py:_find_best_gt_match semantics; crowd absorption is an extension
    (a crowd can absorb any number of detections).
    """

    def match_one(ious, crowd_over, gt_ignore, gt_crowd, det_valid, thresholds):
        # ious/crowd_over (D, G); gt_ignore/gt_crowd (G,); det_valid (D,); thresholds (T,)
        num_gt = ious.shape[1]

        def step(gt_matched, inputs):
            # gt_matched (T, G)
            iou_row, crowd_row, valid = inputs  # (G,), (G,), scalar
            cand = iou_row[None, :] * ~(gt_matched | gt_ignore[None, :])  # (T, G)
            m = jnp.argmax(cand, axis=-1)  # (T,)
            val = jnp.take_along_axis(cand, m[:, None], axis=-1)[:, 0]
            ok = (val > thresholds) & valid
            gt_matched = gt_matched | (jax.nn.one_hot(m, num_gt, dtype=bool) & ok[:, None])
            # unmatched detection covering a crowd gt above threshold -> ignore it
            crowd_val = jnp.max(jnp.where(gt_crowd[None, :], crowd_row[None, :], 0.0), axis=-1)
            crowd_hit = (crowd_val > thresholds) & valid & ~ok
            return gt_matched, (ok, crowd_hit)

        init = jnp.zeros((thresholds.shape[0], num_gt), dtype=bool)
        _, (det_matches, det_crowd) = jax.lax.scan(step, init, (ious, crowd_over, det_valid))  # (D, T) each
        return det_matches.T, det_crowd.T  # (T, D)

    # vmap over pairs (E) then area ranges (A)
    f = jax.vmap(match_one, in_axes=(0, 0, 0, 0, 0, None))  # over E
    f = jax.vmap(f, in_axes=(None, None, 0, None, None, None))  # over A
    return jax.jit(f)


def _mask_iou_ioa(masks1: np.ndarray, masks2: np.ndarray):
    """(IoU, IoA) between boolean masks, one shared intersection matmul.

    IoA = intersection over the *first* mask's area — COCO's detection-vs-crowd
    overlap; computed together with IoU so the (N, H*W) @ (H*W, M) product runs once.
    """
    m1 = jnp.asarray(masks1).reshape(masks1.shape[0], -1).astype(jnp.float32)
    m2 = jnp.asarray(masks2).reshape(masks2.shape[0], -1).astype(jnp.float32)
    inter = m1 @ m2.T
    area1 = m1.sum(-1)[:, None]
    union = area1 + m2.sum(-1)[None, :] - inter
    return inter / jnp.clip(union, 1e-9), inter / jnp.clip(area1, 1e-9)


def _box_iou_ioa(boxes1: Array, boxes2: Array):
    """(IoU, IoA) between box sets, one shared intersection computation."""
    boxes1 = jnp.asarray(boxes1, dtype=jnp.float32).reshape(-1, 4)
    boxes2 = jnp.asarray(boxes2, dtype=jnp.float32).reshape(-1, 4)
    inter, union = _inter_union(boxes1, boxes2)
    return inter / (union + 1e-7), inter / (box_area(boxes1)[:, None] + 1e-7)


class MeanAveragePrecision(Metric):
    """COCO mAP/mAR over box (or mask) detections.

    Update takes the standard list-of-dicts: preds with ``boxes``(or ``masks``)/
    ``scores``/``labels``, target with ``boxes``(or ``masks``)/``labels`` and
    optional ``iscrowd``. Compute returns the COCO summary dict (map, map_50,
    map_75, map_small/medium/large, mar_1/10/100, mar_small/medium/large,
    map_per_class, mar_100_per_class, classes).

    Example:
        >>> from torchmetrics_tpu.detection import MeanAveragePrecision
        >>> import jax.numpy as jnp
        >>> preds = [{"boxes": jnp.asarray([[10.0, 10.0, 20.0, 20.0]]),
        ...           "scores": jnp.asarray([0.8]), "labels": jnp.asarray([0])}]
        >>> target = [{"boxes": jnp.asarray([[12.0, 10.0, 22.0, 20.0]]),
        ...            "labels": jnp.asarray([0])}]
        >>> m = MeanAveragePrecision()
        >>> m.update(preds, target)
        >>> result = m.compute()
        >>> round(float(result["map"]), 4), round(float(result["map_50"]), 4)
        (0.4, 1.0)
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        allowed_iou_types = ("segm", "bbox")
        if iou_type not in allowed_iou_types:
            raise ValueError(f"Expected argument `iou_type` to be one of {allowed_iou_types} but got {iou_type}")
        self.iou_type = iou_type
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.0, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        self.bbox_area_ranges = {
            "all": (float(0**2), float(1e5**2)),
            "small": (float(0**2), float(32**2)),
            "medium": (float(32**2), float(96**2)),
            "large": (float(96**2), float(1e5**2)),
        }

        self.add_state("detections", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruths", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_crowds", default=[], dist_reduce_fx=None)

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        _input_validator(preds, target, iou_type=self.iou_type)
        key = "boxes" if self.iou_type == "bbox" else "masks"
        for item in preds:
            det = self._get_safe_item_values(item[key])
            self.detections.append(det)
            self.detection_labels.append(np.asarray(item["labels"]).reshape(-1).astype(np.int64))
            self.detection_scores.append(np.asarray(item["scores"]).reshape(-1).astype(np.float32))
        for item in target:
            gt = self._get_safe_item_values(item[key])
            self.groundtruths.append(gt)
            labels = np.asarray(item["labels"]).reshape(-1).astype(np.int64)
            self.groundtruth_labels.append(labels)
            crowds = np.asarray(item.get("iscrowd", np.zeros(len(labels)))).reshape(-1).astype(bool)
            self.groundtruth_crowds.append(crowds)

    def _get_safe_item_values(self, item) -> np.ndarray:
        if self.iou_type == "bbox":
            boxes = _fix_empty_tensors(item)
            if boxes.size > 0:
                boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            return np.asarray(boxes, dtype=np.float32).reshape(-1, 4)
        return np.asarray(item, dtype=bool)

    def _get_classes(self) -> List[int]:
        labels = [np.asarray(lab) for lab in self.detection_labels + self.groundtruth_labels]
        if labels:
            return sorted(np.unique(np.concatenate([lab.reshape(-1) for lab in labels])).astype(int).tolist())
        return []

    def _areas(self, items: np.ndarray) -> np.ndarray:
        if self.iou_type == "bbox":
            if not items.size:
                return np.zeros(0, dtype=np.float32)
            # plain numpy: this runs once per (image, class) pair in the host
            # loop, where a jnp box_area call would cost a device round-trip
            b = np.asarray(items, dtype=np.float32).reshape(-1, 4)
            return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        return items.reshape(items.shape[0], -1).sum(-1).astype(np.float32) if items.shape[0] else np.zeros(0)

    def _build_pairs(self, classes: List[int]):
        """Pad all (image, class) evaluation pairs into fixed grids."""
        max_det = self.max_detection_thresholds[-1]
        pairs = []  # (img, class, det_idx sorted desc truncated, gt_idx)
        for i in range(len(self.groundtruths)):
            det_labels = self.detection_labels[i]
            gt_labels = self.groundtruth_labels[i]
            for ci, c in enumerate(classes):
                det_idx = np.nonzero(det_labels == c)[0]
                gt_idx = np.nonzero(gt_labels == c)[0]
                if len(det_idx) == 0 and len(gt_idx) == 0:
                    continue
                order = np.argsort(-self.detection_scores[i][det_idx], kind="stable")
                det_idx = det_idx[order][:max_det]
                pairs.append((i, ci, det_idx, gt_idx))
        if not pairs:
            return None
        d_max = max(1, max(len(p[2]) for p in pairs))
        g_max = max(1, max(len(p[3]) for p in pairs))
        num_pairs = len(pairs)

        det_scores = np.full((num_pairs, d_max), -np.inf, dtype=np.float32)
        det_valid = np.zeros((num_pairs, d_max), dtype=bool)
        det_areas = np.zeros((num_pairs, d_max), dtype=np.float32)
        gt_valid = np.zeros((num_pairs, g_max), dtype=bool)
        gt_crowd = np.zeros((num_pairs, g_max), dtype=bool)
        gt_areas = np.zeros((num_pairs, g_max), dtype=np.float32)
        pair_class = np.zeros(num_pairs, dtype=np.int64)

        if self.iou_type == "bbox":
            det_items = np.zeros((num_pairs, d_max, 4), dtype=np.float32)
            gt_items = np.zeros((num_pairs, g_max, 4), dtype=np.float32)
        else:
            shapes = [d.shape[1:] for d in self.detections + self.groundtruths if d.shape[0]]
            h = max((s[0] for s in shapes), default=1)
            w = max((s[1] for s in shapes), default=1)
            det_items = np.zeros((num_pairs, d_max, h, w), dtype=bool)
            gt_items = np.zeros((num_pairs, g_max, h, w), dtype=bool)

        for e, (i, ci, det_idx, gt_idx) in enumerate(pairs):
            nd, ng = len(det_idx), len(gt_idx)
            pair_class[e] = ci
            det_valid[e, :nd] = True
            gt_valid[e, :ng] = True
            det_scores[e, :nd] = self.detection_scores[i][det_idx]
            gt_crowd[e, :ng] = self.groundtruth_crowds[i][gt_idx]
            det = self.detections[i][det_idx]
            gt = self.groundtruths[i][gt_idx]
            det_areas[e, :nd] = self._areas(det)
            gt_areas[e, :ng] = self._areas(gt)
            if self.iou_type == "bbox":
                det_items[e, :nd] = det
                gt_items[e, :ng] = gt
            else:
                det_items[e, :nd, : det.shape[1] if nd else 0, : det.shape[2] if nd else 0] = det
                gt_items[e, :ng, : gt.shape[1] if ng else 0, : gt.shape[2] if ng else 0] = gt

        # one batched IoU over all pairs; zero-padded items yield IoU 0 and are
        # masked out of matching anyway (det_valid / gt_ignore)
        # one batched pass computes both IoU and the crowd overlap (inter / det_area,
        # COCO semantics) — the intersection product is shared
        pair_fn = _box_iou_ioa if self.iou_type == "bbox" else _mask_iou_ioa
        ious, crowd_over = jax.vmap(pair_fn)(jnp.asarray(det_items), jnp.asarray(gt_items))
        return pair_class, det_scores, det_valid, det_areas, gt_valid, gt_crowd, gt_areas, ious, crowd_over

    def compute(self) -> dict:
        classes = self._get_classes()
        precision, recall = self._calculate(classes)
        res = self._summarize_results(precision, recall)

        map_per_class = np.full(1, -1.0)
        mar_per_class = np.full(1, -1.0)
        if self.class_metrics and classes:
            maps, mars = [], []
            for ci in range(len(classes)):
                cls_res = self._summarize_results(precision[:, :, ci : ci + 1], recall[:, ci : ci + 1])
                maps.append(cls_res["map"])
                mars.append(cls_res[f"mar_{self.max_detection_thresholds[-1]}"])
            map_per_class = np.asarray(maps)
            mar_per_class = np.asarray(mars)
        res["map_per_class"] = jnp.asarray(map_per_class, dtype=jnp.float32)
        res[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(mar_per_class, dtype=jnp.float32)
        res["classes"] = jnp.asarray(classes, dtype=jnp.int32)
        return {k: (jnp.asarray(v, dtype=jnp.float32) if not isinstance(v, jnp.ndarray) else v) for k, v in res.items()}

    def _calculate(self, classes: List[int]):
        """Precision (T,R,K,A,M) and recall (T,K,A,M) tables, -1 where undefined."""
        num_t = len(self.iou_thresholds)
        num_r = len(self.rec_thresholds)
        num_k = max(len(classes), 1)
        num_a = len(self.bbox_area_ranges)
        num_m = len(self.max_detection_thresholds)
        precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
        recall = -np.ones((num_t, num_k, num_a, num_m))

        built = self._build_pairs(classes)
        if built is None:
            return precision, recall
        pair_class, det_scores, det_valid, det_areas, gt_valid, gt_crowd, gt_areas, ious, crowd_over = built

        # per-area ground-truth ignore masks (A, E, G)
        ranges = list(self.bbox_area_ranges.values())
        gt_ignore = np.stack(
            [~gt_valid | gt_crowd | (gt_areas < lo) | (gt_areas > hi) for lo, hi in ranges]
        )
        det_out_of_range = np.stack(
            [(det_areas < lo) | (det_areas > hi) for lo, hi in ranges]
        )  # (A, E, D)

        kernel = _matching_kernel(num_t)
        det_matches, det_crowd = kernel(
            ious,
            crowd_over,
            jnp.asarray(gt_ignore),
            jnp.asarray(gt_crowd),
            jnp.asarray(det_valid),
            jnp.asarray(self.iou_thresholds, dtype=jnp.float32),
        )  # (A, E, T, D) each
        det_matches = np.asarray(det_matches)
        det_crowd = np.asarray(det_crowd)

        # unmatched out-of-range, crowd-absorbed, or padded detections are ignored
        det_ignore = (
            (~det_matches & det_out_of_range[:, :, None, :])
            | det_crowd
            | ~det_valid[None, :, None, :]
        )

        rec_thrs = np.asarray(self.rec_thresholds)
        for ci in range(len(classes)):
            sel = pair_class == ci
            if not sel.any():
                continue
            scores_c = det_scores[sel]  # (Ec, D)
            for ai in range(num_a):
                npig = int((~gt_ignore[ai][sel] & gt_valid[sel]).sum())
                if npig == 0:
                    continue
                matches_c = det_matches[ai][sel]  # (Ec, T, D)
                ignore_c = det_ignore[ai][sel]  # (Ec, T, D)
                for mi, max_det in enumerate(self.max_detection_thresholds):
                    pos_ok = np.zeros_like(scores_c, dtype=bool)
                    pos_ok[:, :max_det] = True
                    take = pos_ok & (scores_c > -np.inf)
                    flat_scores = scores_c[take]
                    flat_matches = np.stack([matches_c[:, t, :][take] for t in range(num_t)])  # (T, N)
                    flat_ignore = np.stack([ignore_c[:, t, :][take] for t in range(num_t)])
                    order = np.argsort(-flat_scores, kind="stable")
                    flat_scores = flat_scores[order]
                    flat_matches = flat_matches[:, order]
                    flat_ignore = flat_ignore[:, order]

                    tps = flat_matches & ~flat_ignore
                    fps = ~flat_matches & ~flat_ignore
                    tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
                    fp_sum = np.cumsum(fps, axis=1).astype(np.float64)
                    for ti in range(num_t):
                        tp = tp_sum[ti]
                        fp = fp_sum[ti]
                        rc = tp / npig
                        pr = tp / (fp + tp + np.finfo(np.float64).eps)
                        recall[ti, ci, ai, mi] = rc[-1] if len(tp) else 0
                        # precision envelope (monotone non-increasing from the right)
                        pr = np.maximum.accumulate(pr[::-1])[::-1]
                        inds = np.searchsorted(rc, rec_thrs, side="left")
                        prec = np.zeros(num_r)
                        valid_inds = inds < len(pr)
                        prec[valid_inds] = pr[inds[valid_inds]]
                        precision[ti, :, ci, ai, mi] = prec
        return precision, recall

    def _summarize(self, precision, recall, avg_prec=True, iou_threshold=None, area_range="all", max_dets=100):
        area_idx = list(self.bbox_area_ranges.keys()).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = precision
            if iou_threshold is not None:
                ti = self.iou_thresholds.index(iou_threshold)
                prec = prec[ti : ti + 1]
            prec = prec[:, :, :, area_idx, mdet_idx]
        else:
            prec = recall
            if iou_threshold is not None:
                ti = self.iou_thresholds.index(iou_threshold)
                prec = prec[ti : ti + 1]
            prec = prec[:, :, area_idx, mdet_idx]
        valid = prec[prec > -1]
        return float(valid.mean()) if valid.size else -1.0

    def _summarize_results(self, precision, recall) -> dict:
        last_max_det = self.max_detection_thresholds[-1]
        res = {
            "map": self._summarize(precision, recall, True, max_dets=last_max_det),
            "map_small": self._summarize(precision, recall, True, area_range="small", max_dets=last_max_det),
            "map_medium": self._summarize(precision, recall, True, area_range="medium", max_dets=last_max_det),
            "map_large": self._summarize(precision, recall, True, area_range="large", max_dets=last_max_det),
        }
        res["map_50"] = (
            self._summarize(precision, recall, True, iou_threshold=0.5, max_dets=last_max_det)
            if 0.5 in self.iou_thresholds
            else -1.0
        )
        res["map_75"] = (
            self._summarize(precision, recall, True, iou_threshold=0.75, max_dets=last_max_det)
            if 0.75 in self.iou_thresholds
            else -1.0
        )
        for max_det in self.max_detection_thresholds:
            res[f"mar_{max_det}"] = self._summarize(precision, recall, False, max_dets=max_det)
        res["mar_small"] = self._summarize(precision, recall, False, area_range="small", max_dets=last_max_det)
        res["mar_medium"] = self._summarize(precision, recall, False, area_range="medium", max_dets=last_max_det)
        res["mar_large"] = self._summarize(precision, recall, False, area_range="large", max_dets=last_max_det)
        return res
