"""Modular IoU-family metrics (reference detection/{iou,giou,diou,ciou}.py).

One base class parameterised by the pairwise function; states accumulate the
per-image IoU matrices (list state) plus ground-truth labels for the optional
per-class breakdown.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.functional.detection.iou import (
    box_convert,
    box_iou,
    complete_box_iou,
    distance_box_iou,
    generalized_box_iou,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class IntersectionOverUnion(Metric):
    """Mean pairwise IoU over matching-label box pairs (reference detection/iou.py:28-200).

    Example:
        >>> from torchmetrics_tpu.detection import IntersectionOverUnion
        >>> import jax.numpy as jnp
        >>> preds = [{"boxes": jnp.asarray([[10.0, 10.0, 20.0, 20.0]]),
        ...           "scores": jnp.asarray([0.8]), "labels": jnp.asarray([0])}]
        >>> target = [{"boxes": jnp.asarray([[12.0, 10.0, 22.0, 20.0]]),
        ...            "labels": jnp.asarray([0])}]
        >>> iou = IntersectionOverUnion()
        >>> iou.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in iou.compute().items()}
        {'iou': 0.6667}
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = True

    _iou_type: str = "iou"
    _invalid_val: float = -1.0
    _pairwise_fn: Callable = staticmethod(box_iou)

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_threshold: Optional[float] = None,
        class_metrics: bool = False,
        respect_labels: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_threshold = iou_threshold
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(respect_labels, bool):
            raise ValueError("Expected argument `respect_labels` to be a boolean")
        self.respect_labels = respect_labels

        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        self.add_state("iou_matrix", default=[], dist_reduce_fx=None)

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        _input_validator(preds, target, ignore_score=True)
        for p, t in zip(preds, target):
            det_boxes = self._get_safe_item_values(p["boxes"])
            gt_boxes = self._get_safe_item_values(t["boxes"])
            t_labels = jnp.asarray(t["labels"]).reshape(-1)
            p_labels = jnp.asarray(p["labels"]).reshape(-1)
            self.groundtruth_labels.append(t_labels)

            iou_matrix = type(self)._pairwise_fn(det_boxes, gt_boxes)  # N x M
            if self.iou_threshold is not None:
                iou_matrix = jnp.where(iou_matrix < self.iou_threshold, self._invalid_val, iou_matrix)
            if self.respect_labels and iou_matrix.size:
                label_eq = p_labels[:, None] == t_labels[None, :]
                iou_matrix = jnp.where(label_eq, iou_matrix, self._invalid_val)
            self.iou_matrix.append(iou_matrix)

    def _get_safe_item_values(self, boxes: Array) -> Array:
        boxes = _fix_empty_tensors(boxes)
        if boxes.size > 0:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def compute(self) -> dict:
        valid = [mat[mat != self._invalid_val] for mat in self.iou_matrix]
        flat = jnp.concatenate([v.reshape(-1) for v in valid]) if valid else jnp.zeros(0)
        score = jnp.mean(flat) if flat.size else jnp.asarray(0.0)
        results: Dict[str, Array] = {f"{self._iou_type}": score}

        if self.class_metrics:
            gt_labels = dim_zero_cat(self.groundtruth_labels)
            classes = np.unique(np.asarray(gt_labels)).tolist() if gt_labels.size else []
            for cl in classes:
                masked_iou, observed = jnp.zeros_like(score), jnp.zeros_like(score)
                for mat, gt_lab in zip(self.iou_matrix, self.groundtruth_labels):
                    scores = mat[:, np.asarray(gt_lab) == cl]
                    masked_iou = masked_iou + jnp.sum(jnp.where(scores != self._invalid_val, scores, 0.0))
                    observed = observed + jnp.sum(scores != self._invalid_val)
                results.update({f"{self._iou_type}/cl_{int(cl)}": masked_iou / observed})
        return results


class GeneralizedIntersectionOverUnion(IntersectionOverUnion):
    """GIOU variant of :class:`IntersectionOverUnion`.

    Example:
        >>> from torchmetrics_tpu.detection import GeneralizedIntersectionOverUnion
        >>> import jax.numpy as jnp
        >>> preds = [{"boxes": jnp.asarray([[10.0, 10.0, 20.0, 20.0]]),
        ...           "scores": jnp.asarray([0.8]), "labels": jnp.asarray([0])}]
        >>> target = [{"boxes": jnp.asarray([[12.0, 10.0, 22.0, 20.0]]),
        ...            "labels": jnp.asarray([0])}]
        >>> m = GeneralizedIntersectionOverUnion()
        >>> m.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in m.compute().items()}
        {'giou': 0.6667}
    """

    _iou_type: str = "giou"
    _invalid_val: float = -1.0
    _pairwise_fn = staticmethod(generalized_box_iou)


class DistanceIntersectionOverUnion(IntersectionOverUnion):
    """DIOU variant of :class:`IntersectionOverUnion`.

    Example:
        >>> from torchmetrics_tpu.detection import DistanceIntersectionOverUnion
        >>> import jax.numpy as jnp
        >>> preds = [{"boxes": jnp.asarray([[10.0, 10.0, 20.0, 20.0]]),
        ...           "scores": jnp.asarray([0.8]), "labels": jnp.asarray([0])}]
        >>> target = [{"boxes": jnp.asarray([[12.0, 10.0, 22.0, 20.0]]),
        ...            "labels": jnp.asarray([0])}]
        >>> m = DistanceIntersectionOverUnion()
        >>> m.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in m.compute().items()}
        {'diou': 0.6503}
    """

    _iou_type: str = "diou"
    _invalid_val: float = -1.0
    _pairwise_fn = staticmethod(distance_box_iou)


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    """CIOU variant of :class:`IntersectionOverUnion`.

    Example:
        >>> from torchmetrics_tpu.detection import CompleteIntersectionOverUnion
        >>> import jax.numpy as jnp
        >>> preds = [{"boxes": jnp.asarray([[10.0, 10.0, 20.0, 20.0]]),
        ...           "scores": jnp.asarray([0.8]), "labels": jnp.asarray([0])}]
        >>> target = [{"boxes": jnp.asarray([[12.0, 10.0, 22.0, 20.0]]),
        ...            "labels": jnp.asarray([0])}]
        >>> m = CompleteIntersectionOverUnion()
        >>> m.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in m.compute().items()}
        {'ciou': 0.6503}
    """

    _iou_type: str = "ciou"
    _invalid_val: float = -2.0
    _pairwise_fn = staticmethod(complete_box_iou)
