from torchmetrics_tpu.detection.iou import (  # noqa: F401
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
)
from torchmetrics_tpu.detection.mean_ap import MeanAveragePrecision  # noqa: F401
from torchmetrics_tpu.detection.panoptic_qualities import (  # noqa: F401
    ModifiedPanopticQuality,
    PanopticQuality,
)

__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PanopticQuality",
]
