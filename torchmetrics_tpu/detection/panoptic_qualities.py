"""Modular PanopticQuality / ModifiedPanopticQuality (reference detection/panoptic_qualities.py:40-295)."""
from __future__ import annotations

from typing import Any, Collection

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.detection.panoptic_quality import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _preprocess_inputs,
    _validate_inputs,
)
from torchmetrics_tpu.metric import Metric


class PanopticQuality(Metric):
    """Panoptic quality over (category, instance) maps.

    States are the four per-category accumulators (sum-reduced across devices);
    all segment extraction happens at update time.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import PanopticQuality
        >>> preds = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])
        >>> target = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [0, 0], [1, 0]]])
        >>> pq = PanopticQuality(things={0}, stuffs={1})
        >>> pq.update(preds, target)
        >>> round(float(pq.compute()), 4)
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        return_sq_and_rq: bool = False,
        return_per_class: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        things, stuffs = _parse_categories(things, stuffs)
        self.things = things
        self.stuffs = stuffs
        self.void_color = _get_void_color(things, stuffs)
        self.cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
        self.allow_unknown_preds_category = allow_unknown_preds_category
        self.return_sq_and_rq = return_sq_and_rq
        self.return_per_class = return_per_class

        num_categories = len(things) + len(stuffs)
        self.add_state("iou_sum", default=jnp.zeros(num_categories), dist_reduce_fx="sum")
        self.add_state("true_positives", default=jnp.zeros(num_categories, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_positives", default=jnp.zeros(num_categories, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_negatives", default=jnp.zeros(num_categories, dtype=jnp.int32), dist_reduce_fx="sum")

    def _update_stats(self, preds: Array, target: Array, modified_metric_stuffs=None) -> None:
        preds = np.asarray(preds)
        target = np.asarray(target)
        _validate_inputs(preds, target)
        flatten_preds = _preprocess_inputs(
            self.things, self.stuffs, preds, self.void_color, self.allow_unknown_preds_category
        )
        flatten_target = _preprocess_inputs(self.things, self.stuffs, target, self.void_color, True)
        iou_sum, tp, fp, fn = _panoptic_quality_update(
            flatten_preds, flatten_target, self.cat_id_to_continuous_id, self.void_color, modified_metric_stuffs
        )
        self.iou_sum = self.iou_sum + iou_sum
        self.true_positives = self.true_positives + tp.astype(self.true_positives.dtype)
        self.false_positives = self.false_positives + fp.astype(self.false_positives.dtype)
        self.false_negatives = self.false_negatives + fn.astype(self.false_negatives.dtype)

    def update(self, preds: Array, target: Array) -> None:
        self._update_stats(preds, target)

    def compute(self) -> Array:
        pq, sq, rq, pq_avg, sq_avg, rq_avg = _panoptic_quality_compute(
            self.iou_sum, self.true_positives, self.false_positives, self.false_negatives
        )
        if self.return_per_class:
            if self.return_sq_and_rq:
                return jnp.stack((pq, sq, rq), axis=-1)
            return pq.reshape(1, -1)
        if self.return_sq_and_rq:
            return jnp.stack((pq_avg, sq_avg, rq_avg))
        return pq_avg


class ModifiedPanopticQuality(PanopticQuality):
    """PQ with the modified stuff formula (reference detection/panoptic_qualities.py:295+).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import ModifiedPanopticQuality
        >>> preds = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])
        >>> target = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [0, 0], [1, 0]]])
        >>> mpq = ModifiedPanopticQuality(things={0}, stuffs={1})
        >>> mpq.update(preds, target)
        >>> round(float(mpq.compute()), 4)
        0.625
    """

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            things=things,
            stuffs=stuffs,
            allow_unknown_preds_category=allow_unknown_preds_category,
            **kwargs,
        )

    def update(self, preds: Array, target: Array) -> None:
        self._update_stats(preds, target, modified_metric_stuffs=self.stuffs)

    def compute(self) -> Array:
        _, _, _, pq_avg, _, _ = _panoptic_quality_compute(
            self.iou_sum, self.true_positives, self.false_positives, self.false_negatives
        )
        return pq_avg
