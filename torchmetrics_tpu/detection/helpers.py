"""Input validation for detection metrics (reference detection/helpers.py)."""
from __future__ import annotations

from typing import Dict, Sequence

import jax.numpy as jnp
import numpy as np


def _fix_empty_tensors(boxes) -> jnp.ndarray:
    """Empty tensors get a (0, 4) shape so pairwise ops stay well-formed."""
    boxes = jnp.asarray(boxes, dtype=jnp.float32)
    if boxes.size == 0 and boxes.ndim == 1:
        return boxes.reshape(0, 4)
    return boxes


def _input_validator(
    preds: Sequence[Dict],
    targets: Sequence[Dict],
    iou_type: str = "bbox",
    ignore_score: bool = False,
) -> None:
    """Check list-of-dicts detection inputs (reference detection/helpers.py:24-72)."""
    item_val_name = "boxes" if iou_type == "bbox" else "masks"

    if not isinstance(preds, Sequence):
        raise ValueError(f"Expected argument `preds` to be of type Sequence, but got {preds}")
    if not isinstance(targets, Sequence):
        raise ValueError(f"Expected argument `target` to be of type Sequence, but got {targets}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )

    for k in [item_val_name, "labels"] + (["scores"] if not ignore_score else []):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in [item_val_name, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    for i, item in enumerate(targets):
        n_gt = np.asarray(item[item_val_name]).shape[0] if np.asarray(item[item_val_name]).size else 0
        n_lab = np.asarray(item["labels"]).reshape(-1).shape[0]
        if n_gt != n_lab:
            raise ValueError(
                f"Input '{item_val_name}' and labels of sample {i} in targets have a"
                f" different length (expected {n_gt} labels, got {n_lab})"
            )
    for i, item in enumerate(preds):
        n_det = np.asarray(item[item_val_name]).shape[0] if np.asarray(item[item_val_name]).size else 0
        n_lab = np.asarray(item["labels"]).reshape(-1).shape[0]
        if not ignore_score:
            n_sc = np.asarray(item["scores"]).reshape(-1).shape[0]
            if n_det != n_lab or n_det != n_sc:
                raise ValueError(
                    f"Input '{item_val_name}', labels and scores of sample {i} in predictions have a"
                    f" different length (expected {n_det} labels and scores, got {n_lab} labels and {n_sc})"
                )
        elif n_det != n_lab:
            raise ValueError(
                f"Input '{item_val_name}' and labels of sample {i} in predictions have a"
                f" different length (expected {n_det} labels, got {n_lab})"
            )
