"""ClasswiseWrapper (reference wrappers/classwise.py:31): label per-class outputs."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class ClasswiseWrapper(WrapperMetric):
    """Split a per-class vector output into a labeled dict (reference wrappers/classwise.py:31).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import ClasswiseWrapper
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> wrapped = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> wrapped.update(preds, jnp.asarray([0, 1, 2, 0]))
        >>> {k: round(float(v), 4) for k, v in wrapped.compute().items()}
        {'multiclassaccuracy_0': 0.5, 'multiclassaccuracy_1': 1.0, 'multiclassaccuracy_2': 1.0}
    """

    def __init__(
        self,
        metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels
        self._prefix = prefix
        self._postfix = postfix

    def _convert(self, x: Array) -> Dict[str, Array]:
        name = self.metric.__class__.__name__.lower()
        prefix = self._prefix or (name + "_" if self._prefix is None and self._postfix is None else "")
        postfix = self._postfix or ""
        if self.labels is None:
            return {f"{prefix}{i}{postfix}": val for i, val in enumerate(x)}
        return {f"{prefix}{lab}{postfix}": val for lab, val in zip(self.labels, x)}

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        return self._convert(self.metric(*args, **kwargs))

    def reset(self) -> None:
        self.metric.reset()

    def state(self) -> Dict[str, Any]:
        return self.metric.state()

    def load_state(self, state: Dict[str, Any], update_count: Optional[int] = None) -> None:
        self.metric.load_state(state, update_count=update_count)
        self._computed = None
        self._update_count = self._restored_count(update_count)

    # ------------------------------------------------------ pure/functional API
    # state IS the base metric's state; only the compute output is relabeled

    def functional_init(self) -> Dict[str, Any]:
        return self.metric.init_state()

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.metric.functional_update(state, *args, **kwargs)

    def functional_sync(self, state: Dict[str, Any], axis_name: Any = None) -> Dict[str, Any]:
        return self.metric.functional_sync(state, axis_name)

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any], counts: Any = None) -> Dict[str, Any]:
        return self.metric.merge_states(a, b, counts=counts)

    def functional_compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        return self._convert(self.metric.functional_compute(state))
