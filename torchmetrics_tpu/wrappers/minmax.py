"""MinMaxMetric (reference wrappers/minmax.py:29): track running min/max of compute."""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class MinMaxMetric(WrapperMetric):
    """Track the running min/max of a base metric's compute (reference wrappers/minmax.py:29).

    Example:
        >>> from torchmetrics_tpu.wrappers import MinMaxMetric
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> mm = MinMaxMetric(BinaryAccuracy())
        >>> mm.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in mm.compute().items()}
        {'max': 0.5, 'min': 0.5, 'raw': 0.5}
    """

    # NB no full_state_update flag: Metric.forward's routing is bypassed by the
    # explicit forward() override below

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `torchmetrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.add_state("min_val", jnp.asarray(jnp.inf), dist_reduce_fx="min")
        self.add_state("max_val", jnp.asarray(-jnp.inf), dist_reduce_fx="max")

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        """Batch value + running extrema.

        The reference routes through ``Metric.forward``'s full-state path with
        UNREGISTERED min/max tensors (reference minmax.py:78-79): min/max are
        monotone over every compute (batch computes included), but the batch
        reset/restore cycle silently LOSES the base metric's accumulated state
        after each forward — ``compute()`` after N forwards returns the last
        batch, not the accumulation. We keep per-forward outputs identical
        (raw = batch value, min/max = extrema over batch values) while the
        base metric's own forward preserves global accumulation, so a final
        ``compute()`` reports the accumulated value — a deliberate fix of the
        reference's multi-forward state loss.
        """
        batch_raw = self._base_metric.forward(*args, **kwargs)
        # the override bypasses Metric.forward's bookkeeping: count the update
        # and invalidate any cached compute() result ourselves
        self._update_count += 1
        self._computed = None
        self._track(batch_raw)
        return {"raw": jnp.asarray(batch_raw), "max": self.max_val, "min": self.min_val}

    def _track(self, val: Array) -> None:
        val = self._check_scalar(val)
        self.max_val = jnp.where(self.max_val < val, jnp.asarray(val, dtype=jnp.float32), self.max_val)
        self.min_val = jnp.where(self.min_val > val, jnp.asarray(val, dtype=jnp.float32), self.min_val)

    def compute(self) -> Dict[str, Array]:
        val = self._base_metric.compute()
        self._track(val)
        return {"raw": jnp.asarray(val), "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()

    def state(self) -> Dict[str, Any]:
        """Live state in the FUNCTIONAL layout (base state nested + extrema +
        count), so ``state()``/``merge_states``/``functional_compute``/
        ``load_state`` interoperate across the dual API."""
        return {
            # field-only export (no reserved "_update_count" key): the nested
            # base state must stay tree-compatible with functional_init's
            # layout and with merge_states outputs; the wrapper carries the
            # authoritative count itself
            "base": self._base_metric._copy_state_dict(),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "count": jnp.asarray(self._update_count, jnp.int32),
        }

    def load_state(self, state: Dict[str, Any], update_count: Optional[int] = None) -> None:
        # the exported state carries the true count; an explicit update_count
        # (the base-class signature) overrides the bookkeeping counter
        count = self._restored_count(update_count, fallback=int(state["count"]))
        self._base_metric.load_state(state["base"], update_count=count)
        self.min_val = state["min_val"]
        self.max_val = state["max_val"]
        self._update_count = count
        self._computed = None

    # ------------------------------------------------------ pure/functional API
    #
    # Extrema are data, not side effects, on this path: they move when a value
    # is *produced into the state* — i.e. on ``functional_forward`` (batch
    # values). ``functional_compute`` is a pure read: it folds the current
    # accumulated value into the reported extrema but cannot persist that fold
    # (call ``functional_forward``, or carry the returned state, if you need
    # compute-time values tracked like the OO ``compute`` does via ``_track``).

    def functional_init(self) -> Dict[str, Any]:
        """Fresh wrapper state: base metric state + running extrema + count."""
        if self._base_metric.full_state_update is not False:
            raise ValueError(
                "The functional MinMaxMetric path requires a base metric with"
                " full_state_update=False: its update is decomposed into fresh-batch-state"
                f" + merge, but {type(self._base_metric).__name__}.full_state_update is"
                f" {self._base_metric.full_state_update}."
            )
        from torchmetrics_tpu.wrappers.abstract import _require_mergeable_tensor_states

        _require_mergeable_tensor_states(self._base_metric, "MinMaxMetric")
        return {
            "base": self._base_metric.init_state(),
            "min_val": jnp.asarray(jnp.inf),
            "max_val": jnp.asarray(-jnp.inf),
            "count": jnp.asarray(0, jnp.int32),
        }

    def _absorb(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> tuple:
        import jax

        base_batch = self._base_metric.functional_update(self._base_metric.init_state(), *args, **kwargs)
        merged = self._base_metric.merge_states(
            state["base"], base_batch, counts=(jnp.maximum(state["count"], 1), 1)
        )
        # the very first batch must REPLACE the default state, not average with
        # it — a phantom (1,1)-weighted default would dilute "mean" states
        is_first = state["count"] == 0
        merged = jax.tree_util.tree_map(lambda b, m: jnp.where(is_first, b, m), base_batch, merged)
        return base_batch, merged

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure update: absorb the batch into the base state (count-weighted).

        Mirrors the OO ``update`` — extrema move only on forward/compute
        (they track *computed* values, reference minmax.py:66-79).
        """
        _, merged = self._absorb(state, *args, **kwargs)
        return {
            "base": merged,
            "min_val": state["min_val"],
            "max_val": state["max_val"],
            "count": state["count"] + 1,
        }

    def functional_forward(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> tuple:
        """Pure forward: ``(state, batch) -> (state', {'raw','min','max'})``.

        The batch value is the base metric on the batch alone; extrema fold the
        batch value in; the base state keeps the global accumulation.
        """
        base_batch, merged = self._absorb(state, *args, **kwargs)
        batch_val = self._check_scalar(self._base_metric.functional_compute(base_batch))
        new_min, new_max = self._fold_extrema(state, batch_val)
        new_state = {
            "base": merged,
            "min_val": new_min,
            "max_val": new_max,
            "count": state["count"] + 1,
        }
        return new_state, {"raw": batch_val, "max": new_state["max_val"], "min": new_state["min_val"]}

    def functional_sync(self, state: Dict[str, Any], axis_name: Any = None) -> Dict[str, Any]:
        """Declared-collective sync: base state by its own reductions, extrema
        by min/max (matching the OO states' dist_reduce_fx, minmax.py:38-39)."""
        from torchmetrics_tpu.parallel.sync import sync_states

        axis = axis_name or self.sync_axis
        extrema = sync_states(
            {"min_val": state["min_val"], "max_val": state["max_val"], "count": state["count"]},
            {"min_val": "min", "max_val": "max", "count": "sum"},
            axis,
        )
        return {
            "base": self._base_metric.functional_sync(state["base"], axis),
            "min_val": extrema["min_val"],
            "max_val": extrema["max_val"],
            # summed: after sync the base state holds global totals, so future
            # count-weighted merges must weigh it by the global update count
            "count": extrema["count"],
        }

    def functional_compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        """Accumulated base value with extrema folded over it — a pure read:
        the fold is reported but NOT persisted (see the class-path note above)."""
        val = self._check_scalar(self._base_metric.functional_compute(state["base"]))
        new_min, new_max = self._fold_extrema(state, val)
        return {"raw": val, "max": new_max, "min": new_min}

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any], counts: Any = None) -> Dict[str, Any]:
        """Merge two wrapper states: base by its own reductions (count-weighted
        by each side's own update count), extrema by NaN-ignoring min/max.

        A side that saw no updates contributes nothing — its default base state
        must REPLACE rather than dilute "mean" reductions (same guard as
        :meth:`_absorb`'s first-batch case).
        """
        import jax

        na, nb = a["count"], b["count"]
        base = self._base_metric.merge_states(
            a["base"], b["base"], counts=(jnp.maximum(na, 1), jnp.maximum(nb, 1))
        )
        base = jax.tree_util.tree_map(lambda bb, mm: jnp.where(na == 0, bb, mm), b["base"], base)
        base = jax.tree_util.tree_map(lambda aa, mm: jnp.where(nb == 0, aa, mm), a["base"], base)
        return {
            "base": base,
            "min_val": jnp.fmin(a["min_val"], b["min_val"]),
            "max_val": jnp.fmax(a["max_val"], b["max_val"]),
            "count": na + nb,
        }

    @staticmethod
    def _fold_extrema(state: Dict[str, Any], val: Array) -> tuple:
        """Strict-comparison fold like the OO ``_track`` — a NaN value leaves
        the extrema untouched (``jnp.minimum/maximum`` would propagate it)."""
        v = val.astype(jnp.float32)
        new_min = jnp.where(state["min_val"] > v, v, state["min_val"])
        new_max = jnp.where(state["max_val"] < v, v, state["max_val"])
        return new_min, new_max

    @staticmethod
    def _check_scalar(raw: Any) -> Array:
        """Same scalar contract as the OO ``_track`` (shape is static in-trace)."""
        if not (isinstance(raw, (float, int)) or (hasattr(raw, "size") and raw.size == 1)):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {raw}.")
        # a size-1 but non-0-d value (shape (1,)) would broadcast the () extrema
        # states up to (1,), changing the carry structure under jit/scan
        return jnp.asarray(raw).reshape(())
