"""MinMaxMetric (reference wrappers/minmax.py:29): track running min/max of compute."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class MinMaxMetric(WrapperMetric):
    """Track the running min/max of a base metric's compute (reference wrappers/minmax.py:29).

    Example:
        >>> from torchmetrics_tpu.wrappers import MinMaxMetric
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> mm = MinMaxMetric(BinaryAccuracy())
        >>> mm.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in mm.compute().items()}
        {'max': 0.5, 'min': 0.5, 'raw': 0.5}
    """

    # NB no full_state_update flag: Metric.forward's routing is bypassed by the
    # explicit forward() override below

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `torchmetrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.add_state("min_val", jnp.asarray(jnp.inf), dist_reduce_fx="min")
        self.add_state("max_val", jnp.asarray(-jnp.inf), dist_reduce_fx="max")

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        """Batch value + running extrema.

        The reference routes through ``Metric.forward``'s full-state path with
        UNREGISTERED min/max tensors (reference minmax.py:78-79): min/max are
        monotone over every compute (batch computes included), but the batch
        reset/restore cycle silently LOSES the base metric's accumulated state
        after each forward — ``compute()`` after N forwards returns the last
        batch, not the accumulation. We keep per-forward outputs identical
        (raw = batch value, min/max = extrema over batch values) while the
        base metric's own forward preserves global accumulation, so a final
        ``compute()`` reports the accumulated value — a deliberate fix of the
        reference's multi-forward state loss.
        """
        batch_raw = self._base_metric.forward(*args, **kwargs)
        # the override bypasses Metric.forward's bookkeeping: count the update
        # and invalidate any cached compute() result ourselves
        self._update_count += 1
        self._computed = None
        self._track(batch_raw)
        return {"raw": jnp.asarray(batch_raw), "max": self.max_val, "min": self.min_val}

    def _track(self, val: Array) -> None:
        if not (hasattr(val, "size") and val.size == 1):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.where(self.max_val < val, jnp.asarray(val, dtype=jnp.float32), self.max_val)
        self.min_val = jnp.where(self.min_val > val, jnp.asarray(val, dtype=jnp.float32), self.min_val)

    def compute(self) -> Dict[str, Array]:
        val = self._base_metric.compute()
        self._track(val)
        return {"raw": jnp.asarray(val), "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()

    # ------------------------------------------------------ pure/functional API

    def functional_init(self) -> Dict[str, Any]:
        """Fresh wrapper state: base metric state + running extrema."""
        return {
            "base": self._base_metric.init_state(),
            "min_val": jnp.asarray(jnp.inf),
            "max_val": jnp.asarray(-jnp.inf),
        }

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure update: absorb the batch into the base state.

        Mirrors the OO ``update`` — extrema move only on forward/compute
        (they track *computed* values, reference minmax.py:66-79).
        """
        base_batch = self._base_metric.functional_update(self._base_metric.init_state(), *args, **kwargs)
        return {
            "base": self._base_metric.merge_states(state["base"], base_batch),
            "min_val": state["min_val"],
            "max_val": state["max_val"],
        }

    def functional_forward(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> tuple:
        """Pure forward: ``(state, batch) -> (state', {'raw','min','max'})``.

        The batch value is the base metric on the batch alone; extrema fold the
        batch value in; the base state keeps the global accumulation.
        """
        base_batch = self._base_metric.functional_update(self._base_metric.init_state(), *args, **kwargs)
        batch_val = jnp.asarray(self._base_metric.functional_compute(base_batch))
        new_state = {
            "base": self._base_metric.merge_states(state["base"], base_batch),
            "min_val": jnp.minimum(state["min_val"], batch_val.astype(jnp.float32)),
            "max_val": jnp.maximum(state["max_val"], batch_val.astype(jnp.float32)),
        }
        return new_state, {"raw": batch_val, "max": new_state["max_val"], "min": new_state["min_val"]}

    def functional_compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        """Accumulated base value with extrema folded over it (jit-safe)."""
        val = jnp.asarray(self._base_metric.functional_compute(state["base"]))
        return {
            "raw": val,
            "max": jnp.maximum(state["max_val"], val.astype(jnp.float32)),
            "min": jnp.minimum(state["min_val"], val.astype(jnp.float32)),
        }
