"""FeatureShare wrapper (reference wrappers/feature_share.py:26-120).

Model-backed metrics in this build hold a ``feature_extractor`` (or other
named) callable; FeatureShare replaces every member's callable with ONE shared
memoizing wrapper so a single forward pass serves FID + KID + IS etc.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric


class NetworkCache:
    """Memoize a feature function by input object identity (reference :26-42).

    The reference wraps the network forward in ``lru_cache``; jax arrays are
    unhashable, so the cache keys on ``id`` + shape of the input, which covers
    the FeatureShare pattern (the SAME batch array passed to several metrics).
    """

    def __init__(self, network: Callable, max_size: int = 100) -> None:
        self.network = network
        self.max_size = max_size
        self._cache: "OrderedDict[tuple, Any]" = OrderedDict()

    @staticmethod
    def _key_part(v: Any) -> Any:
        # arrays are unhashable: key them by identity+shape
        if hasattr(v, "shape"):
            return (id(v), v.shape)
        return v

    def __call__(self, x, *args: Any, **kwargs: Any) -> Any:
        key = (
            self._key_part(x),
            tuple(self._key_part(a) for a in args),
            tuple(sorted((k, self._key_part(v)) for k, v in kwargs.items())),
        )
        if key in self._cache:
            self._cache.move_to_end(key)
            return self._cache[key][-1]
        out = self.network(x, *args, **kwargs)
        # keep the inputs alive alongside the result: as long as the entry
        # exists their ids cannot be recycled by new allocations
        self._cache[key] = (x, args, kwargs, out)
        if len(self._cache) > self.max_size:
            self._cache.popitem(last=False)
        return out


class FeatureShare(MetricCollection):
    """MetricCollection that shares one cached feature extractor across members.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import FeatureShare
        >>> from torchmetrics_tpu.image import FrechetInceptionDistance, KernelInceptionDistance
        >>> extractor = lambda x: x.mean(axis=(2, 3))
        >>> fs = FeatureShare([
        ...     FrechetInceptionDistance(feature_extractor=extractor, num_features=3),
        ...     KernelInceptionDistance(feature_extractor=extractor, subsets=2, subset_size=3),
        ... ])  # one extractor pass serves both metrics
        >>> real = (jnp.arange(4 * 3 * 8 * 8).reshape(4, 3, 8, 8) % 255) / 255.0
        >>> fs.update(real, real=True)
        >>> fs.update(real * 0.7, real=False)
        >>> sorted(fs.compute().keys())
        ['FrechetInceptionDistance', 'KernelInceptionDistance']
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        max_cache_size: Optional[int] = None,
        extractor_attribute: str = "feature_extractor",
    ) -> None:
        super().__init__(metrics)
        if max_cache_size is None:
            max_cache_size = len(self)
        if not isinstance(max_cache_size, int):
            raise TypeError(f"max_cache_size should be an integer, but got {max_cache_size}")
        self.extractor_attribute = extractor_attribute

        extractors: List[Callable] = []
        for name, metric in self.items(keep_base=True, copy_state=False):
            fn = getattr(metric, extractor_attribute, None)
            if fn is None:
                raise AttributeError(
                    f"Tried to extract the network to share from the metric {name}, but it had no attribute"
                    f" {extractor_attribute!r}. Please raise an issue or pick metrics exposing one."
                )
            extractors.append(fn)

        shared = NetworkCache(extractors[0], max_size=max_cache_size)
        for _, metric in self.items(keep_base=True, copy_state=False):
            setattr(metric, extractor_attribute, shared)
