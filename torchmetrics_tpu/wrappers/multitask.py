"""MultitaskWrapper (reference wrappers/multitask.py:30): dict of task → metric."""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class MultitaskWrapper(WrapperMetric):
    """Dict of task name → metric, updated from per-task preds/target dicts (reference wrappers/multitask.py:30).

    Example:
        >>> from torchmetrics_tpu.wrappers import MultitaskWrapper
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> mt = MultitaskWrapper({"cls": BinaryAccuracy(), "reg": MeanSquaredError()})
        >>> mt.update({"cls": preds, "reg": preds},
        ...           {"cls": target, "reg": target.astype(jnp.float32)})
        >>> {k: round(float(v), 4) for k, v in mt.compute().items()}
        {'cls': 0.5, 'reg': 0.2325}
    """

    def __init__(self, task_metrics: Dict[str, Union[Metric, MetricCollection]], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(task_metrics, dict):
            raise TypeError(f"Expected argument `task_metrics` to be a dict. Found task_metrics = {task_metrics}")
        for metric in task_metrics.values():
            if not isinstance(metric, (Metric, MetricCollection)):
                raise TypeError(
                    "Expected each task's metric to be a Metric or a MetricCollection. "
                    f"Found a metric of type {type(metric)}"
                )
        self.task_metrics = task_metrics

    def items(self):
        return self.task_metrics.items()

    def keys(self):
        return self.task_metrics.keys()

    def values(self):
        return self.task_metrics.values()

    def _check_all_tasks_present(self, task_dict: Dict[str, Any]) -> None:
        if task_dict.keys() != self.task_metrics.keys():
            raise ValueError(
                f"Expected arguments to have the same keys as the wrapped `task_metrics`. Found task_preds/targets keys"
                f" = {task_dict.keys()} and task_metrics.keys() = {self.task_metrics.keys()}"
            )

    def update(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> None:
        self._check_all_tasks_present(task_preds)
        self._check_all_tasks_present(task_targets)
        for task_name, metric in self.task_metrics.items():
            metric.update(task_preds[task_name], task_targets[task_name])

    def compute(self) -> Dict[str, Any]:
        return {task_name: metric.compute() for task_name, metric in self.task_metrics.items()}

    def forward(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> Dict[str, Any]:
        self._check_all_tasks_present(task_preds)
        self._check_all_tasks_present(task_targets)
        return {
            task_name: metric(task_preds[task_name], task_targets[task_name])
            for task_name, metric in self.task_metrics.items()
        }

    def reset(self) -> None:
        for metric in self.task_metrics.values():
            metric.reset()
        super().reset()

    # ------------------------------------------------------ pure/functional API
    # states are a dict keyed by task; each task delegates to its metric's (or
    # collection's) own pure core

    def functional_init(self) -> Dict[str, Any]:
        return {task: m.functional_init() for task, m in self.task_metrics.items()}

    def functional_update(
        self, states: Dict[str, Any], task_preds: Dict[str, Any], task_targets: Dict[str, Any]
    ) -> Dict[str, Any]:
        self._check_all_tasks_present(task_preds)
        self._check_all_tasks_present(task_targets)
        return {
            task: m.functional_update(states[task], task_preds[task], task_targets[task])
            for task, m in self.task_metrics.items()
        }

    def functional_sync(self, states: Dict[str, Any], axis_name: Any = None) -> Dict[str, Any]:
        return {task: m.functional_sync(states[task], axis_name) for task, m in self.task_metrics.items()}

    def functional_compute(self, states: Dict[str, Any]) -> Dict[str, Any]:
        return {task: m.functional_compute(states[task]) for task, m in self.task_metrics.items()}

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any], counts: Any = None) -> Dict[str, Any]:
        return {task: m.merge_states(a[task], b[task], counts=counts) for task, m in self.task_metrics.items()}

    def state(self) -> Dict[str, Any]:
        return {task: m.state() for task, m in self.task_metrics.items()}

    def load_state(self, states: Dict[str, Any], update_count: Optional[int] = None) -> None:
        for task, m in self.task_metrics.items():
            m.load_state(states[task], update_count=update_count)
        self._computed = None
        self._update_count = self._restored_count(update_count)

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MultitaskWrapper":
        import copy

        multitask_copy = copy.deepcopy(self)
        if prefix is not None:
            multitask_copy.task_metrics = {prefix + k: v for k, v in multitask_copy.task_metrics.items()}
        if postfix is not None:
            multitask_copy.task_metrics = {k + postfix: v for k, v in multitask_copy.task_metrics.items()}
        return multitask_copy
