"""MultioutputWrapper (reference wrappers/multioutput.py:43): per-output clones."""
from __future__ import annotations

from copy import deepcopy
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows containing a NaN in any tensor (reference multioutput.py:24-32)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(sentinel.shape[0], dtype=bool)
    for tensor in tensors:
        permuted = tensor.reshape(tensor.shape[0], -1)
        nan_idxs = nan_idxs | jnp.isnan(permuted).any(axis=1)
    return nan_idxs


class MultioutputWrapper(WrapperMetric):
    """Apply a metric independently per output dimension (last axis by default).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MultioutputWrapper
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> mo = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> mo.update(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]), jnp.asarray([[1.0, 1.0], [4.0, 3.0]]))
        >>> jnp.round(mo.compute(), 4).tolist()
        [0.5, 1.0]
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple]:
        """Slice inputs along the output dimension (reference :84-108)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            selected_args = [
                jnp.take(arg, jnp.asarray([i]), axis=self.output_dim) if hasattr(arg, "shape") else arg for arg in args
            ]
            selected_kwargs = {
                k: (jnp.take(v, jnp.asarray([i]), axis=self.output_dim) if hasattr(v, "shape") else v)
                for k, v in kwargs.items()
            }
            if self.remove_nans:
                tensors = [a for a in selected_args if hasattr(a, "shape")] + [
                    v for v in selected_kwargs.values() if hasattr(v, "shape")
                ]
                if tensors:
                    nan_idxs = np.asarray(_get_nan_indices(*tensors))
                    selected_args = [
                        jnp.asarray(np.asarray(a)[~nan_idxs]) if hasattr(a, "shape") else a for a in selected_args
                    ]
                    selected_kwargs = {
                        k: (jnp.asarray(np.asarray(v)[~nan_idxs]) if hasattr(v, "shape") else v)
                        for k, v in selected_kwargs.items()
                    }
            if self.squeeze_outputs:
                selected_args = [a.squeeze(self.output_dim) if hasattr(a, "shape") else a for a in selected_args]
                selected_kwargs = {
                    k: (v.squeeze(self.output_dim) if hasattr(v, "shape") else v) for k, v in selected_kwargs.items()
                }
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        return jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], 0)

    def forward(self, *args: Any, **kwargs: Any) -> Array:
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped)
        ]
        if any(r is None for r in results):
            return None
        return jnp.stack([jnp.asarray(r) for r in results], 0)

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()

    # ------------------------------------------------------ pure/functional API
    #
    # The output axis becomes a vmap axis: state leaves carry a leading
    # ``num_outputs`` dimension and one vmapped update/compute serves every
    # output — no per-output Python loop inside the traced step. NaN-row
    # removal is data-dependent shape, so it stays on the eager OO path;
    # construct with ``remove_nans=False`` to use the functional API.

    def functional_init(self) -> Any:
        """Fresh default state with a leading ``num_outputs`` axis per leaf."""
        from torchmetrics_tpu.wrappers.abstract import _stacked_init

        return _stacked_init(self.metrics[0], len(self.metrics))

    def _vmap_payload(self, args: Tuple, kwargs: dict) -> Tuple[Any, Any]:
        def prep(x: Any) -> Any:
            if hasattr(x, "shape") and getattr(x, "ndim", 0) > 0:
                moved = jnp.moveaxis(jnp.asarray(x), self.output_dim, 0)
                if moved.shape[0] != len(self.metrics):
                    raise ValueError(
                        f"Expected {len(self.metrics)} outputs along dim {self.output_dim}"
                        f" but got {moved.shape[0]}"
                    )
                return moved
            return x

        payload = (tuple(prep(a) for a in args), {k: prep(v) for k, v in kwargs.items()})
        import jax

        axes = jax.tree_util.tree_map(
            lambda x: 0 if hasattr(x, "shape") and getattr(x, "ndim", 0) > 0 else None, payload
        )
        return payload, axes

    def functional_update(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        """Pure vmapped update over the output axis: ``(stacked_state, batch) -> stacked_state'``."""
        if self.remove_nans:
            raise ValueError(
                "The functional path requires remove_nans=False: NaN-row removal changes shapes"
                " per output and cannot be traced. Construct MultioutputWrapper(..., remove_nans=False)."
            )
        if not self.squeeze_outputs:
            raise ValueError(
                "The functional path requires squeeze_outputs=True: vmapping over the output"
                " axis always removes it, so a kept size-1 axis cannot be honored."
            )
        import jax

        base = self.metrics[0]
        payload, axes = self._vmap_payload(args, kwargs)

        def _one(st: Any, p: Tuple) -> Any:
            return base.functional_update(st, *p[0], **p[1])

        return jax.vmap(_one, in_axes=(0, axes))(state, payload)

    def functional_sync(self, state: Any, axis_name: Any = None) -> Any:
        """Per-output declared-collective sync, vmapped over the output axis."""
        import jax

        base = self.metrics[0]
        axis = axis_name or self.sync_axis
        return jax.vmap(lambda st: base.functional_sync(st, axis))(state)

    def merge_states(self, a: Any, b: Any, counts: Any = None) -> Any:
        """Output-wise merge: sum/mean/max/min folds are elementwise, so the
        base metric's merge applies directly to the stacked leaves."""
        return self.metrics[0].merge_states(a, b, counts=counts)

    def state(self) -> Any:
        """Live per-output states in the functional stacked layout (or a
        ``replicates`` snapshot list for list-state bases)."""
        from torchmetrics_tpu.wrappers.abstract import _stacked_state

        return _stacked_state(self.metrics)

    def load_state(self, state: Any, update_count: Optional[int] = None) -> None:
        from torchmetrics_tpu.wrappers.abstract import _load_stacked_state

        _load_stacked_state(self.metrics, state, update_count=update_count)
        self._computed = None
        self._update_count = self._restored_count(update_count)

    def functional_compute(self, state: Any) -> Array:
        """Stacked per-output values, matching :meth:`compute`'s layout."""
        import jax

        return jax.vmap(self.metrics[0].functional_compute)(state)
