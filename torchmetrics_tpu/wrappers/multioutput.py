"""MultioutputWrapper (reference wrappers/multioutput.py:43): per-output clones."""
from __future__ import annotations

from copy import deepcopy
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows containing a NaN in any tensor (reference multioutput.py:24-32)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(sentinel.shape[0], dtype=bool)
    for tensor in tensors:
        permuted = tensor.reshape(tensor.shape[0], -1)
        nan_idxs = nan_idxs | jnp.isnan(permuted).any(axis=1)
    return nan_idxs


class MultioutputWrapper(WrapperMetric):
    """Apply a metric independently per output dimension (last axis by default).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MultioutputWrapper
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> mo = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> mo.update(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]), jnp.asarray([[1.0, 1.0], [4.0, 3.0]]))
        >>> jnp.round(mo.compute(), 4).tolist()
        [0.5, 1.0]
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple]:
        """Slice inputs along the output dimension (reference :84-108)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            selected_args = [
                jnp.take(arg, jnp.asarray([i]), axis=self.output_dim) if hasattr(arg, "shape") else arg for arg in args
            ]
            selected_kwargs = {
                k: (jnp.take(v, jnp.asarray([i]), axis=self.output_dim) if hasattr(v, "shape") else v)
                for k, v in kwargs.items()
            }
            if self.remove_nans:
                tensors = [a for a in selected_args if hasattr(a, "shape")] + [
                    v for v in selected_kwargs.values() if hasattr(v, "shape")
                ]
                if tensors:
                    nan_idxs = np.asarray(_get_nan_indices(*tensors))
                    selected_args = [
                        jnp.asarray(np.asarray(a)[~nan_idxs]) if hasattr(a, "shape") else a for a in selected_args
                    ]
                    selected_kwargs = {
                        k: (jnp.asarray(np.asarray(v)[~nan_idxs]) if hasattr(v, "shape") else v)
                        for k, v in selected_kwargs.items()
                    }
            if self.squeeze_outputs:
                selected_args = [a.squeeze(self.output_dim) if hasattr(a, "shape") else a for a in selected_args]
                selected_kwargs = {
                    k: (v.squeeze(self.output_dim) if hasattr(v, "shape") else v) for k, v in selected_kwargs.items()
                }
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        return jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], 0)

    def forward(self, *args: Any, **kwargs: Any) -> Array:
        reshaped = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped)
        ]
        if any(r is None for r in results):
            return None
        return jnp.stack([jnp.asarray(r) for r in results], 0)

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()
