"""Running wrapper (reference wrappers/running.py:27).

Sliding window over the last ``window`` update calls: one state-set snapshot per
slot; compute folds window states back via the base metric's ``_reduce_states``.
Requires ``full_state_update=False`` on the base metric.
"""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class Running(WrapperMetric):
    """Sliding-window view of the last ``window`` updates (reference wrappers/running.py:27).

    Example:
        >>> from torchmetrics_tpu.wrappers import Running
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> running = Running(SumMetric(), window=2)
        >>> for v in [1.0, 2.0, 3.0]:
        ...     running.update(v)
        >>> float(running.compute())  # only the last two updates
        5.0
    """

    def __init__(self, base_metric: Metric, window: int = 5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        if base_metric.full_state_update is not False:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self._window_states: list = []  # ring of state snapshots, newest last

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Snapshot the state produced by this update alone (reference :99-116)."""
        batch_state = self.base_metric.functional_update(self.base_metric.init_state(), *args, **kwargs)
        self._window_states.append(batch_state)
        if len(self._window_states) > self.window:
            self._window_states.pop(0)
        self._computed = None
        self._update_count += 1

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Batch value + window accumulation."""
        batch_state = self.base_metric.functional_update(self.base_metric.init_state(), *args, **kwargs)
        batch_val = self.base_metric.functional_compute(batch_state)
        self._window_states.append(batch_state)
        if len(self._window_states) > self.window:
            self._window_states.pop(0)
        self._computed = None
        self._update_count += 1
        return batch_val

    def compute(self) -> Any:
        """Fold window states with the base metric's merge protocol."""
        if not self._window_states:
            return self.base_metric.functional_compute(self.base_metric.init_state())
        acc = self._window_states[0]
        for st in self._window_states[1:]:
            acc = self.base_metric.merge_states(acc, st)
        return self.base_metric.functional_compute(acc)

    def reset(self) -> None:
        super().reset()
        self._window_states = []
        self.base_metric.reset()
