"""Running wrapper (reference wrappers/running.py:27).

Sliding window over the last ``window`` update calls: one state-set snapshot per
slot; compute folds window states back via the base metric's ``_reduce_states``.
Requires ``full_state_update=False`` on the base metric.
"""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class Running(WrapperMetric):
    """Sliding-window view of the last ``window`` updates (reference wrappers/running.py:27).

    Example:
        >>> from torchmetrics_tpu.wrappers import Running
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> running = Running(SumMetric(), window=2)
        >>> for v in [1.0, 2.0, 3.0]:
        ...     running.update(v)
        >>> float(running.compute())  # only the last two updates
        5.0
    """

    def __init__(self, base_metric: Metric, window: int = 5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        if base_metric.full_state_update is not False:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self._window_states: list = []  # ring of state snapshots, newest last

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Snapshot the state produced by this update alone (reference :99-116)."""
        batch_state = self.base_metric.functional_update(self.base_metric.init_state(), *args, **kwargs)
        self._window_states.append(batch_state)
        if len(self._window_states) > self.window:
            self._window_states.pop(0)
        self._computed = None

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Batch value + window accumulation."""
        batch_state = self.base_metric.functional_update(self.base_metric.init_state(), *args, **kwargs)
        batch_val = self.base_metric.functional_compute(batch_state)
        self._window_states.append(batch_state)
        if len(self._window_states) > self.window:
            self._window_states.pop(0)
        self._computed = None
        self._update_count += 1
        return batch_val

    def compute(self) -> Any:
        """Fold window states with the base metric's merge protocol.

        Count-weighted (``counts=(k, 1)``): each snapshot holds one update, so
        "mean"-reduced states average uniformly over the window.
        """
        if not self._window_states:
            return self.base_metric.functional_compute(self.base_metric.init_state())
        acc = self._window_states[0]
        for k, st in enumerate(self._window_states[1:], start=1):
            acc = self.base_metric.merge_states(acc, st, counts=(k, 1))
        return self.base_metric.functional_compute(acc)

    def reset(self) -> None:
        super().reset()
        self._window_states = []
        self.base_metric.reset()

    def state(self) -> Any:
        """Live window in the FUNCTIONAL ring layout: ``(window, ...)`` slots
        (default-padded at the front, newest last) + total update count.

        List/"cat"-state bases cannot stack into a static ring (per-slot list
        lengths differ); their window is exported as a ``snapshots`` list of
        per-update state dicts instead."""
        import jax
        import jax.numpy as jnp

        base = self.base_metric
        # the functional layout's count doubles as the ring VALIDITY counter
        # (slot i is valid iff i >= window - min(count, window)). The lifetime
        # _update_count satisfies that invariant in normal operation and is
        # exported so restore preserves it — but load_state(..., update_count=)
        # may override the bookkeeping to a value inconsistent with the ring;
        # exporting THAT would make every later restore/functional_compute
        # drop real slots or resurrect default pads, so fall back to the
        # actual fill whenever the invariant is broken
        fill = len(self._window_states)
        lifetime = self._update_count
        count = jnp.asarray(lifetime if min(lifetime, self.window) == fill else fill, jnp.int32)
        if any(isinstance(d, list) for d in base._defaults.values()):
            return {"snapshots": [dict(s) for s in self._window_states], "count": count}
        pad = [base.init_state() for _ in range(self.window - len(self._window_states))]
        seq = pad + list(self._window_states)
        slots = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *seq)
        return {"slots": slots, "count": count}

    def load_state(self, state: Any, update_count: Optional[int] = None) -> None:
        import jax

        # the ring state's count (= number of valid slots, see state()) is
        # authoritative for slot restoration — an explicit update_count must
        # never resurrect default-pad slots as real window states (or drop
        # real ones); it only overrides the bookkeeping counter below
        count = int(state["count"])
        if "snapshots" in state:
            keep = min(self.window, len(state["snapshots"]))
            self._window_states = [dict(s) for s in state["snapshots"][-keep:]] if keep else []
        else:
            slots = state["slots"]
            # index relative to the SOURCE ring's window (its leading dim):
            # real data sits newest-last there, front slots are default pads
            src_window = jax.tree_util.tree_leaves(slots)[0].shape[0]
            n = min(count, src_window, self.window)
            self._window_states = [
                jax.tree_util.tree_map(lambda x, i=i: x[i], slots) for i in range(src_window - n, src_window)
            ]
        self._update_count = self._restored_count(update_count, fallback=count)
        self._computed = None

    # ------------------------------------------------------ pure/functional API
    #
    # The window becomes a static leading axis: state leaves are
    # ``(window, ...)`` rings, an update shifts the newest batch state in (and
    # the oldest out), and compute folds the filled slots oldest-to-newest with
    # the base merge protocol under a validity mask — all trace-safe, so a
    # running metric lives inside a jitted train step. Tensor states only
    # (list/"cat" states have per-slot dynamic shapes).

    def functional_init(self) -> Any:
        """Fresh ring state: ``window``-stacked default states + fill count."""
        import jax.numpy as jnp

        from torchmetrics_tpu.wrappers.abstract import _require_mergeable_tensor_states, _stacked_init

        base = self.base_metric
        _require_mergeable_tensor_states(base, "Running")
        return {
            "slots": _stacked_init(base, self.window),
            "count": jnp.asarray(0, jnp.int32),
        }

    def functional_update(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        """Pure update: shift the batch state into the newest ring slot."""
        new_state, _ = self._functional_step(state, *args, **kwargs)
        return new_state

    def functional_forward(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        """Pure forward: ``(state, batch) -> (state', batch_value)``."""
        return self._functional_step(state, *args, compute_batch=True, **kwargs)

    def _functional_step(self, state: Any, *args: Any, compute_batch: bool = False, **kwargs: Any) -> Any:
        import jax
        import jax.numpy as jnp

        base = self.base_metric
        batch_state = base.functional_update(base.init_state(), *args, **kwargs)
        slots = jax.tree_util.tree_map(
            lambda s, b: jnp.concatenate([s[1:], b[None]], axis=0), state["slots"], batch_state
        )
        new_state = {"slots": slots, "count": state["count"] + 1}
        batch_val = base.functional_compute(batch_state) if compute_batch else None
        return new_state, batch_val

    def functional_sync(self, state: Any, axis_name: Any = None) -> Any:
        """Per-slot declared-collective sync, vmapped over the window axis."""
        import jax

        base = self.base_metric
        axis = axis_name or self.sync_axis
        slots = jax.vmap(lambda st: base.functional_sync(st, axis))(state["slots"])
        return {"slots": slots, "count": state["count"]}

    def merge_states(self, a: Any, b: Any, counts: Any = None) -> Any:
        raise NotImplementedError(
            "Running state is a sliding-window ring of per-update states; merging two rings"
            " has no defined order. Advance the window with functional_update/functional_forward"
            " instead."
        )

    def functional_compute(self, state: Any) -> Any:
        """Fold filled ring slots oldest-to-newest via the base merge protocol.

        The fold is count-weighted (``counts=(k, 1)``): each slot holds exactly
        one update, so "mean"-reduced states come out uniformly weighted over
        the window rather than exponentially decayed.
        """
        import jax
        import jax.numpy as jnp

        base = self.base_metric
        slots, count = state["slots"], state["count"]
        n_valid = jnp.minimum(count, self.window)
        # slot i holds the (window - i)-th most recent update; valid slots are
        # the contiguous tail i >= window - n_valid
        acc = jax.tree_util.tree_map(lambda s: s[0], slots)
        started = 0 >= self.window - n_valid
        n_acc = started.astype(jnp.int32)
        for i in range(1, self.window):
            slot_i = jax.tree_util.tree_map(lambda s: s[i], slots)
            valid_i = i >= self.window - n_valid
            merged = base.merge_states(acc, slot_i, counts=(jnp.maximum(n_acc, 1), 1))
            take_merged = started & valid_i
            acc = jax.tree_util.tree_map(
                lambda m, s, a: jnp.where(take_merged, m, jnp.where(valid_i, s, a)), merged, slot_i, acc
            )
            started = started | valid_i
            n_acc = n_acc + valid_i.astype(jnp.int32)
        return base.functional_compute(acc)
