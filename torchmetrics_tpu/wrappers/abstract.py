"""WrapperMetric base (reference wrappers/abstract.py:19-26): disables own sync."""
from typing import Any

from torchmetrics_tpu.metric import Metric


def _require_mergeable_tensor_states(base: Metric, path_name: str) -> None:
    """Reject base metrics whose states cannot be carried through a traced
    merge fold: list states and 'cat'/custom reductions change leaf shapes."""
    bad = [
        name
        for name, fx in base._reductions.items()
        if isinstance(base._defaults.get(name), list) or fx not in ("sum", "mean", "max", "min")
    ]
    if bad:
        raise ValueError(
            f"The functional {path_name} path supports tensor states with sum/mean/max/min"
            f" reductions only; state(s) {bad} use list or 'cat'/custom reductions whose"
            " merges change leaf shapes and cannot be carried through a traced step."
        )


def _stacked_state(metrics: Any) -> Any:
    """Children's live states in the functional stacked ``(n, ...)`` layout,
    falling back to a per-child ``replicates`` snapshot list when list/"cat"
    states make stacking impossible (poisson bootstrap resamples, cat states
    of differing lengths)."""
    import jax
    import jax.numpy as jnp

    states = [m.state() for m in metrics]
    if any(isinstance(d, list) for d in metrics[0]._defaults.values()):
        return {"replicates": states}
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def _load_stacked_state(metrics: Any, state: Any, update_count: Any = None) -> None:
    """Inverse of :func:`_stacked_state`, validating the replicate count —
    jax's eager indexing CLAMPS out-of-bounds, which would silently duplicate
    the last replicate on a count mismatch. ``update_count`` is forwarded to
    every child so wrapper and children agree after a restore."""
    import jax

    if isinstance(state, dict) and "replicates" in state:
        reps = state["replicates"]
        if len(reps) != len(metrics):
            raise ValueError(f"state holds {len(reps)} replicate states but this wrapper has {len(metrics)}")
        for m, st in zip(metrics, reps):
            m.load_state(st, update_count=update_count)
        return
    leaves = jax.tree_util.tree_leaves(state)
    if leaves and leaves[0].shape[:1] != (len(metrics),):
        raise ValueError(
            f"state leading dimension {leaves[0].shape[:1] or 'scalar'} does not match this"
            f" wrapper's {len(metrics)} child metrics"
        )
    for i, m in enumerate(metrics):
        m.load_state(jax.tree_util.tree_map(lambda x, i=i: x[i], state), update_count=update_count)


def _stacked_init(base: Metric, n: int) -> Any:
    """``n`` copies of the base default state stacked along a new leading axis —
    the vmap-ready state layout shared by the wrappers' functional paths."""
    import jax
    import jax.numpy as jnp

    bad = [name for name, default in base._defaults.items() if isinstance(default, list)]
    if bad:
        raise ValueError(
            f"{type(base).__name__} holds list ('cat') state(s) {bad} whose per-update"
            " dynamic shapes cannot be stacked into a static replicate axis; the functional"
            " wrapper paths require tensor states (e.g. capacity-buffered variants)."
        )
    states = [base.init_state() for _ in range(n)]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


class WrapperMetric(Metric):
    """Abstract base for wrappers; the wrapper itself never syncs (children do)."""

    def _wrap_update(self, update):
        return super()._wrap_update(update)

    def sync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def unsync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError

    def compute(self) -> Any:
        raise NotImplementedError
