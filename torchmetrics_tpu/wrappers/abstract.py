"""WrapperMetric base (reference wrappers/abstract.py:19-26): disables own sync."""
from typing import Any

from torchmetrics_tpu.metric import Metric


class WrapperMetric(Metric):
    """Abstract base for wrappers; the wrapper itself never syncs (children do)."""

    def _wrap_update(self, update):
        return super()._wrap_update(update)

    def sync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def unsync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError

    def compute(self) -> Any:
        raise NotImplementedError
