"""BootStrapper (reference wrappers/bootstrapping.py:54).

Maintains ``num_bootstraps`` independent copies of the base metric; every update
feeds each copy a resampled version of the batch (poisson or multinomial
weights). compute → mean/std/quantile/raw over the copies.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson", rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    """Resample indices (reference bootstrapping.py:28-50)."""
    rng = rng or np.random
    if sampling_strategy == "poisson":
        p = rng.poisson(1, size)
        return np.repeat(np.arange(size), p)
    if sampling_strategy == "multinomial":
        return rng.randint(0, size, size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(WrapperMetric):
    """Bootstrapped confidence estimates of a base metric (reference wrappers/bootstrapping.py:54).

    Each update feeds every internal copy a poisson/multinomial resample of the
    batch; compute reports mean/std (and optional quantile/raw) across copies.

    Example:
        >>> from torchmetrics_tpu.wrappers import BootStrapper
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> boot = BootStrapper(BinaryAccuracy(), num_bootstraps=4, seed=42)
        >>> boot.update(preds, target)
        >>> sorted(boot.compute().keys())
        ['mean', 'std']
    """

    full_state_update: Optional[bool] = True
    # eager updates draw fresh host-side RandomState resamples per call; a
    # traced executor replay would freeze one sample pattern forever
    executor_compatible: bool = False

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of torchmetrics_tpu.Metric but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling} but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.RandomState(seed)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample the batch for each bootstrap copy (reference :129-149)."""
        args_sizes = [a.shape[0] for a in args if hasattr(a, "shape") and a.ndim > 0]
        kwargs_sizes = [v.shape[0] for v in kwargs.values() if hasattr(v, "shape") and v.ndim > 0]
        if args_sizes:
            size = args_sizes[0]
        elif kwargs_sizes:
            size = kwargs_sizes[0]
        else:
            raise ValueError("None of the input contained any tensor, so no sampling could be done")
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            if sample_idx.size == 0:
                continue
            new_args = [jnp.asarray(np.asarray(a)[sample_idx]) if hasattr(a, "shape") and a.ndim > 0 else a for a in args]
            new_kwargs = {
                k: jnp.asarray(np.asarray(v)[sample_idx]) if hasattr(v, "shape") and v.ndim > 0 else v
                for k, v in kwargs.items()
            }
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """Mean/std/quantile/raw over bootstrap computes (reference :151-172)."""
        computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(0)
        if self.std:
            output_dict["std"] = computed_vals.std(0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self.update(*args, **kwargs)
        return self.compute()

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()

    # ------------------------------------------------------ pure/functional API
    #
    # TPU-idiomatic bootstrap (SURVEY.md §7 step 5): instead of n deep copies
    # fed by a host-side Python loop, the resample axis becomes a vmap axis —
    # state leaves carry a leading ``num_bootstraps`` dimension and ONE vmapped
    # update/compute serves every replicate inside a jitted step. Resampling
    # must be static-shape under jit, so the functional path draws multinomial
    # (with-replacement, size-n) index matrices; the poisson strategy's
    # variable-length ``np.repeat`` resamples exist only on the eager OO path.

    def functional_init(self) -> Dict[str, Any]:
        """Fresh default state with a leading ``num_bootstraps`` axis per leaf."""
        from torchmetrics_tpu.wrappers.abstract import _stacked_init

        return _stacked_init(self.metrics[0], self.num_bootstraps)

    def functional_update(
        self, state: Dict[str, Any], *args: Any, key: Any = None, indices: Any = None, **kwargs: Any
    ) -> Dict[str, Any]:
        """Pure vmapped update: ``(stacked_state, batch) -> stacked_state'``.

        Pass a ``jax.random`` ``key`` (multinomial strategy only — the static-
        shape resample) or an explicit ``indices`` array of shape
        ``(num_bootstraps, batch)`` selecting each replicate's resample.

        Example:
            >>> import jax, jax.numpy as jnp
            >>> from torchmetrics_tpu import BootStrapper, MeanMetric
            >>> boot = BootStrapper(MeanMetric(), num_bootstraps=4, sampling_strategy="multinomial")
            >>> state = boot.functional_init()
            >>> state = jax.jit(boot.functional_update)(
            ...     state, jnp.asarray([1.0, 2.0, 3.0, 4.0]), key=jax.random.PRNGKey(0))
            >>> out = boot.functional_compute(state)
            >>> sorted(out) == ['mean', 'std'] and bool(out['std'] >= 0)
            True
        """
        import jax

        base = self.metrics[0]
        sizes = [a.shape[0] for a in args if hasattr(a, "shape") and getattr(a, "ndim", 0) > 0]
        sizes += [v.shape[0] for v in kwargs.values() if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0]
        if not sizes:
            raise ValueError("None of the input contained any tensor, so no sampling could be done")
        if indices is None:
            if key is None:
                raise ValueError("functional_update needs either a `key` or an explicit `indices` array")
            if self.sampling_strategy != "multinomial":
                raise ValueError(
                    "The functional bootstrap path requires sampling_strategy='multinomial': poisson"
                    " resamples have data-dependent length and cannot be traced with static shapes."
                )
            size = sizes[0]
            indices = jax.random.randint(key, (self.num_bootstraps, size), 0, size)
        indices = jnp.asarray(indices)
        if indices.ndim != 2 or indices.shape[0] != self.num_bootstraps:
            raise ValueError(
                f"Expected `indices` of shape (num_bootstraps={self.num_bootstraps}, n) but got {indices.shape}"
            )

        def _one(st: Dict[str, Any], idx: Array) -> Dict[str, Any]:
            new_args = [a[idx] if hasattr(a, "shape") and getattr(a, "ndim", 0) > 0 else a for a in args]
            new_kwargs = {
                k: v[idx] if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0 else v for k, v in kwargs.items()
            }
            return base.functional_update(st, *new_args, **new_kwargs)

        return jax.vmap(_one)(state, indices)

    def functional_sync(self, state: Dict[str, Any], axis_name: Any = None) -> Dict[str, Any]:
        """Per-replicate declared-collective sync, vmapped over the resample axis."""
        import jax

        base = self.metrics[0]
        axis = axis_name or self.sync_axis
        return jax.vmap(lambda st: base.functional_sync(st, axis))(state)

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any], counts: Any = None) -> Dict[str, Any]:
        """Replicate-wise merge: sum/mean/max/min folds are elementwise, so the
        base metric's merge applies directly to the stacked leaves."""
        return self.metrics[0].merge_states(a, b, counts=counts)

    def state(self) -> Dict[str, Any]:
        """Live per-replicate states in the functional stacked layout (or a
        ``replicates`` snapshot list for list-state bases / poisson resamples)."""
        from torchmetrics_tpu.wrappers.abstract import _stacked_state

        return _stacked_state(self.metrics)

    def load_state(self, state: Dict[str, Any], update_count: Optional[int] = None) -> None:
        from torchmetrics_tpu.wrappers.abstract import _load_stacked_state

        _load_stacked_state(self.metrics, state, update_count=update_count)
        self._computed = None
        self._update_count = self._restored_count(update_count)

    def functional_compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        """Mean/std/quantile/raw across the vmapped replicate axis."""
        import jax

        base = self.metrics[0]
        vals = jax.vmap(base.functional_compute)(state)
        output_dict: Dict[str, Array] = {}
        if self.mean:
            output_dict["mean"] = jax.tree_util.tree_map(lambda v: v.mean(0), vals)
        if self.std:
            output_dict["std"] = jax.tree_util.tree_map(lambda v: v.std(0, ddof=1), vals)
        if self.quantile is not None:
            output_dict["quantile"] = jax.tree_util.tree_map(lambda v: jnp.quantile(v, self.quantile, axis=0), vals)
        if self.raw:
            output_dict["raw"] = vals
        return output_dict
