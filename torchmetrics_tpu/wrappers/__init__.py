from torchmetrics_tpu.wrappers.abstract import WrapperMetric  # noqa: F401
from torchmetrics_tpu.wrappers.bootstrapping import BootStrapper  # noqa: F401
from torchmetrics_tpu.wrappers.classwise import ClasswiseWrapper  # noqa: F401
from torchmetrics_tpu.wrappers.minmax import MinMaxMetric  # noqa: F401
from torchmetrics_tpu.wrappers.multioutput import MultioutputWrapper  # noqa: F401
from torchmetrics_tpu.wrappers.multitask import MultitaskWrapper  # noqa: F401
from torchmetrics_tpu.wrappers.running import Running  # noqa: F401
from torchmetrics_tpu.wrappers.tracker import MetricTracker  # noqa: F401
from torchmetrics_tpu.wrappers.feature_share import FeatureShare, NetworkCache  # noqa: F401

__all__ = [
    "BootStrapper",
    "ClasswiseWrapper",
    "FeatureShare",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "NetworkCache",
    "Running",
    "WrapperMetric",
]
