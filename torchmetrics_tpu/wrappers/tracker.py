"""MetricTracker (reference wrappers/tracker.py:31).

Tracks a metric (or collection) over a sequence of steps/epochs: ``increment()``
clones the base per step; ``best_metric``/``compute_all`` across steps.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.prints import rank_zero_warn


class MetricTracker:
    """Track a metric (or collection) over epochs/steps (reference wrappers/tracker.py:31).

    Example:
        >>> from torchmetrics_tpu.wrappers import MetricTracker
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> tracker = MetricTracker(BinaryAccuracy())
        >>> for epoch in range(2):
        ...     tracker.increment()
        ...     tracker.update(preds, target)
        >>> round(float(tracker.best_metric()), 4)
        0.5
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool], None] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a torchmetrics_tpu"
                f" `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if maximize is not None:
            if not isinstance(maximize, (bool, list)):
                raise ValueError("Argument `maximize` should either be a single bool or list of bool")
            if isinstance(maximize, list) and not all(isinstance(m, bool) for m in maximize):
                raise ValueError("Argument `maximize` should either be a single bool or list of bool")
            if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
                raise ValueError("The len of argument `maximize` should match the length of the metric collection")
            if isinstance(metric, Metric) and not isinstance(maximize, bool):
                raise ValueError("Argument `maximize` should be a single bool when `metric` is a single Metric")
        else:
            if isinstance(metric, Metric):
                maximize = bool(metric.higher_is_better)
            else:
                maximize = [bool(m.higher_is_better) for m in metric.values()]
        self.maximize = maximize
        self._steps: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        return len(self._steps)

    def increment(self) -> None:
        """Create a fresh copy of the base metric for a new step (reference :103)."""
        self._increment_called = True
        self._steps.append(deepcopy(self._base_metric))
        self._steps[-1].reset()

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called.")

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._steps[-1].update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._steps[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._steps[-1].compute()

    def compute_all(self) -> Any:
        """Values across all steps (reference :139-158)."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._steps]
        if isinstance(self._base_metric, MetricCollection):
            keys = res[0].keys()
            return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
        return jnp.stack([jnp.asarray(r) for r in res], axis=0)

    def reset(self) -> None:
        self._steps[-1].reset()

    def reset_all(self) -> None:
        for metric in self._steps:
            metric.reset()

    def state(self) -> Dict[str, Any]:
        """Per-step states, completing the state()/load_state contract the rest
        of the wrapper family shares (each step is the base metric's layout)."""
        return {"steps": [m.state() for m in self._steps]}

    def load_state(self, state: Dict[str, Any], update_count: Optional[int] = None) -> None:
        # update_count is accepted for base-signature uniformity only — each
        # step is its own lifecycle (MinMax/Running step states carry their own
        # counts), so a single forwarded value would clobber per-step counts
        del update_count
        # build the new steps fully before swapping them in: a bad step state
        # must raise cleanly, not leave a half-loaded tracker behind
        new_steps: List[Union[Metric, MetricCollection]] = []
        for st in state["steps"]:
            m = deepcopy(self._base_metric)
            m.reset()
            m.load_state(st)
            new_steps.append(m)
        self._steps = new_steps
        self._increment_called = bool(self._steps)

    def _best(self, values: Array, maximize: bool) -> Tuple[float, int]:
        idx = int(jnp.argmax(values)) if maximize else int(jnp.argmin(values))
        return float(values[idx]), idx

    def best_metric(
        self, return_step: bool = False
    ) -> Union[float, Tuple[float, int], Dict[str, float], Tuple[Dict[str, float], Dict[str, int]]]:
        """Best value (and optionally step) over tracked steps (reference :160-208)."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else [self.maximize] * len(res)
            values, steps = {}, {}
            for (k, v), m in zip(res.items(), maximize):
                try:
                    values[k], steps[k] = self._best(v, m)
                except (ValueError, TypeError) as error:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}: {error}"
                    )
                    values[k], steps[k] = None, None
            return (values, steps) if return_step else values
        try:
            value, step = self._best(res, bool(self.maximize))
        except (ValueError, TypeError) as error:
            rank_zero_warn(f"Encountered the following error when trying to get the best metric: {error}")
            value, step = None, None
        return (value, step) if return_step else value

    def plot(self, val=None, ax=None):
        """Plot tracked values over steps (reference wrappers/tracker.py:273-330).

        Without ``val``, plots ``compute_all()`` — one line per metric for a
        tracked collection, a single series otherwise.
        """
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute_all()
        return plot_single_or_multi_val(val, ax=ax, name=type(self._base_metric).__name__)
