"""Pairwise distance/similarity helpers (reference functional/pairwise/, 526 LoC).

Batched Gram-matrix computations — pure MXU work: every function is one or two
matmuls plus elementwise ops, computed with fp32 accumulation (`_safe_matmul`).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.compute import _safe_matmul


def _check_input(x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None) -> Tuple[Array, Array, bool]:
    """Validate inputs (reference pairwise/helpers.py)."""
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    if reduction == "mean":
        return distmat.mean(-1)
    if reduction == "sum":
        return distmat.sum(-1)
    if reduction in (None, "none"):
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")


def pairwise_cosine_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """pairwise cosine similarity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import pairwise_cosine_similarity
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        >>> result = pairwise_cosine_similarity(x, y)
        >>> jnp.round(result, 4).tolist()
        [[0.948699951171875, 0.948699951171875, 0.948699951171875], [0.9898999929428101, 0.9898999929428101, 0.9898999929428101]]
    """

    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.float32) if y is not None else None
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    norm_x = jnp.linalg.norm(x, axis=1, keepdims=True)
    norm_y = jnp.linalg.norm(y, axis=1, keepdims=True)
    distance = _safe_matmul(x / norm_x, (y / norm_y).T)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1]))
    return _reduce_distance_matrix(distance, reduction)


def pairwise_euclidean_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """pairwise euclidean distance (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import pairwise_euclidean_distance
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        >>> result = pairwise_euclidean_distance(x, y)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 1.0, 2.2360999584198], [3.605599880218506, 2.2360999584198, 1.0]]
    """

    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.float32) if y is not None else None
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_norm = (x * x).sum(1, keepdims=True)
    y_norm = (y * y).sum(1)
    distance = x_norm + y_norm - 2 * _safe_matmul(x, y.T)
    distance = jnp.sqrt(jnp.clip(distance, min=0.0))
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1]))
    return _reduce_distance_matrix(distance, reduction)


def pairwise_manhattan_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """pairwise manhattan distance (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import pairwise_manhattan_distance
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        >>> result = pairwise_manhattan_distance(x, y)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 1.0, 3.0], [5.0, 3.0, 1.0]]
    """

    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.float32) if y is not None else None
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.abs(x[:, None, :] - y[None, :, :]).sum(-1)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1]))
    return _reduce_distance_matrix(distance, reduction)


def pairwise_linear_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """pairwise linear similarity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import pairwise_linear_similarity
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        >>> result = pairwise_linear_similarity(x, y)
        >>> jnp.round(result, 4).tolist()
        [[3.0, 6.0, 9.0], [7.0, 14.0, 21.0]]
    """

    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.float32) if y is not None else None
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = _safe_matmul(x, y.T)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1]))
    return _reduce_distance_matrix(distance, reduction)


def pairwise_minkowski_distance(
    x: Array,
    y: Optional[Array] = None,
    exponent: float = 2.0,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """pairwise minkowski distance (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import pairwise_minkowski_distance
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        >>> result = pairwise_minkowski_distance(x, y, exponent=3)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 1.0, 2.0801000595092773], [3.271099805831909, 2.0801000595092773, 1.0]]
    """

    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.float32) if y is not None else None
    if not (isinstance(exponent, (float, int)) and exponent >= 1):
        raise ValueError(f"Argument ``exponent`` expected to be a float larger than 1, but got {exponent}")
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = (jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent).sum(-1) ** (1.0 / exponent)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1]))
    return _reduce_distance_matrix(distance, reduction)
