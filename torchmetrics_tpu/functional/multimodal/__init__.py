from torchmetrics_tpu.multimodal.clip_score import (  # noqa: F401
    clip_image_quality_assessment,
    clip_score,
)

__all__ = ["clip_image_quality_assessment", "clip_score"]
