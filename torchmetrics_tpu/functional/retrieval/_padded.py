"""Padded per-query retrieval kernels.

Reference behavior: retrieval/base.py:43-180 + functional/retrieval/*.py. The
reference sorts by query id, splits into ragged per-query chunks and runs a
Python loop; ragged splits don't trace under XLA, so the TPU design packs all
queries into one static ``(num_queries, max_docs)`` grid (pad preds with -inf,
targets with 0) and evaluates EVERY metric as batched masked tensor ops over
that grid — one fused kernel instead of a per-query loop.

All kernels take the grid pre-sorted per row by descending prediction score
(``ranked_target``: the target values in retrieval order) plus the per-query
document counts, and return one value per query.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.compute import _safe_divide


def pad_by_query(indexes: Array, preds: Array, target: Array) -> Tuple[Array, Array, Array]:
    """Pack flat (doc -> query) data into a static ``(Q, L)`` grid.

    Returns ``(preds_pad, target_pad, counts)`` where ``preds_pad`` is -inf and
    ``target_pad`` 0 beyond each query's document count. Runs on host shapes
    (list-state compute path), so numpy-style dynamic shapes are fine here.
    """
    indexes = jnp.asarray(indexes).reshape(-1)
    preds = jnp.asarray(preds, dtype=jnp.float32).reshape(-1)
    target = jnp.asarray(target).reshape(-1)

    order = jnp.argsort(indexes, stable=True)
    indexes, preds, target = indexes[order], preds[order], target[order]

    unique, counts = jnp.unique(indexes, return_counts=True)
    num_queries = int(unique.shape[0])
    max_docs = int(counts.max())

    row = jnp.searchsorted(unique, indexes)
    offsets = jnp.concatenate([jnp.zeros(1, dtype=counts.dtype), jnp.cumsum(counts)[:-1]])
    col = jnp.arange(indexes.shape[0]) - offsets[row]

    preds_pad = jnp.full((num_queries, max_docs), -jnp.inf, dtype=preds.dtype).at[row, col].set(preds)
    target_pad = jnp.zeros((num_queries, max_docs), dtype=jnp.float32).at[row, col].set(target.astype(jnp.float32))
    return preds_pad, target_pad, counts.astype(jnp.int32)


def rank_by_preds(preds_pad: Array, target_pad: Array) -> Tuple[Array, Array]:
    """Sort each row by descending score; returns (ranked_preds, ranked_target)."""
    order = jnp.argsort(-preds_pad, axis=-1, stable=True)
    return jnp.take_along_axis(preds_pad, order, axis=-1), jnp.take_along_axis(target_pad, order, axis=-1)


def _topk_mask(counts: Array, top_k: Optional[int], length: int) -> Array:
    """(Q, L) mask of ranks < min(top_k, count_q)."""
    pos = jnp.arange(length)[None, :]
    k = counts[:, None] if top_k is None else jnp.minimum(top_k, counts[:, None])
    return pos < k


def _grid_stats(ranked_target: Array, counts: Array, top_k: Optional[int]) -> Array:
    """(Q, 4) fused [hits@k, total_rel, inv_hits@k, total_inv] — one sweep
    over the ranked grid through the ``"retrieval_topk_stats"`` kernel seam,
    shared across every padded metric reading the same grid (ops/topk_kernel.py)."""
    from torchmetrics_tpu.ops.topk_kernel import retrieval_topk_stats

    return retrieval_topk_stats(ranked_target, counts, top_k)


def hit_counts(ranked_target: Array, counts: Array, top_k: Optional[int]) -> Array:
    """Number of relevant docs retrieved in the top k of each query."""
    return _grid_stats(ranked_target, counts, top_k)[:, 0]


def precision_padded(
    ranked_target: Array, counts: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """Precision@k per query (reference functional/retrieval/precision.py)."""
    hits = _grid_stats(ranked_target, counts, top_k)[:, 0]
    if top_k is None:
        denom = counts
    elif adaptive_k:
        denom = jnp.minimum(top_k, counts)
    else:
        denom = jnp.full_like(counts, top_k)
    return _safe_divide(hits, denom.astype(hits.dtype))


def recall_padded(ranked_target: Array, counts: Array, top_k: Optional[int] = None) -> Array:
    """Recall@k per query (reference functional/retrieval/recall.py)."""
    stats = _grid_stats(ranked_target, counts, top_k)
    return _safe_divide(stats[:, 0], stats[:, 1])


def fall_out_padded(ranked_target: Array, counts: Array, top_k: Optional[int] = None) -> Array:
    """Fall-out@k per query: non-relevant retrieved / all non-relevant."""
    stats = _grid_stats(ranked_target, counts, top_k)
    return _safe_divide(stats[:, 2], stats[:, 3])


def hit_rate_padded(ranked_target: Array, counts: Array, top_k: Optional[int] = None) -> Array:
    """1.0 if any relevant doc in the top k (reference functional/retrieval/hit_rate.py)."""
    return (_grid_stats(ranked_target, counts, top_k)[:, 0] > 0).astype(jnp.float32)


def average_precision_padded(ranked_target: Array, counts: Array, top_k: Optional[int] = None) -> Array:
    """AP per query: mean of precision@rank over relevant ranks in the top k."""
    mask = _topk_mask(counts, top_k, ranked_target.shape[-1])
    t = ranked_target * mask
    ranks = jnp.arange(1, ranked_target.shape[-1] + 1)[None, :].astype(jnp.float32)
    prec_at_rank = jnp.cumsum(t, axis=-1) / ranks
    return _safe_divide(jnp.sum(t * prec_at_rank, axis=-1), jnp.sum(t, axis=-1))


def reciprocal_rank_padded(ranked_target: Array, counts: Array, top_k: Optional[int] = None) -> Array:
    """RR per query: 1/rank of the first relevant doc in the top k; 0 if none."""
    mask = _topk_mask(counts, top_k, ranked_target.shape[-1])
    ranks = jnp.arange(1, ranked_target.shape[-1] + 1)[None, :].astype(jnp.float32)
    return jnp.max(jnp.where(mask & (ranked_target > 0), 1.0 / ranks, 0.0), axis=-1)


def r_precision_padded(ranked_target: Array, counts: Array) -> Array:
    """Precision at k = number-of-relevant per query."""
    total = jnp.sum(ranked_target, axis=-1)
    pos = jnp.arange(ranked_target.shape[-1])[None, :]
    hits = jnp.sum(ranked_target * (pos < total[:, None]), axis=-1)
    return _safe_divide(hits, total)


def _row_segment_ids(ranked_preds: Array) -> Array:
    """Tie-group ids per row: consecutive equal scores share an id."""
    boundary = ranked_preds[:, 1:] != ranked_preds[:, :-1]
    return jnp.concatenate([jnp.zeros((ranked_preds.shape[0], 1), dtype=jnp.int32), jnp.cumsum(boundary, axis=-1, dtype=jnp.int32)], axis=-1)


def dcg_padded(
    ranked_preds: Array, ranked_target: Array, counts: Array, top_k: Optional[int], ignore_ties: bool
) -> Array:
    """Tie-averaged discounted cumulative gain per query.

    Reference functional/retrieval/ndcg.py:_dcg_sample_scores/_tie_average_dcg:
    tied scores share the average of their positions' discounts. Per-row tie
    groups are reduced with ``segment_sum`` (static segment count = row length)
    instead of the reference's unique/scatter_add, so the whole grid stays one
    traced kernel.
    """
    length = ranked_target.shape[-1]
    pos = jnp.arange(length)[None, :]
    discount = jnp.where(
        pos < (length if top_k is None else min(top_k, length)),
        1.0 / jnp.log2(pos + 2.0),
        0.0,
    ) * jnp.ones((ranked_target.shape[0], 1))

    if ignore_ties:
        return jnp.sum(discount * ranked_target, axis=-1)

    gid = _row_segment_ids(ranked_preds)
    seg_sum = jax.vmap(partial(jax.ops.segment_sum, num_segments=length))
    group_t = seg_sum(ranked_target, gid)
    group_c = seg_sum(jnp.ones_like(ranked_target), gid)
    group_d = seg_sum(discount, gid)
    return jnp.sum(_safe_divide(group_t, group_c) * group_d, axis=-1)


def ndcg_padded(
    ranked_preds: Array, ranked_target: Array, counts: Array, top_k: Optional[int] = None
) -> Array:
    """Normalized DCG per query (reference functional/retrieval/ndcg.py)."""
    gain = dcg_padded(ranked_preds, ranked_target, counts, top_k, ignore_ties=False)
    # padded slots (rank >= count) must sort BELOW any real relevance value —
    # including negatives — so key them to -inf for the ideal ordering
    pos = jnp.arange(ranked_target.shape[-1])[None, :]
    key = jnp.where(pos < counts[:, None], ranked_target, -jnp.inf)
    ideal_target = -jnp.sort(-key, axis=-1)
    ideal_target = jnp.where(jnp.isfinite(ideal_target), ideal_target, 0.0)
    ideal = dcg_padded(ideal_target, ideal_target, counts, top_k, ignore_ties=True)
    return _safe_divide(gain, ideal)


def auroc_padded(
    ranked_preds: Array, ranked_target: Array, counts: Array, top_k: Optional[int] = None
) -> Array:
    """AUROC per query over the top-k retrieved docs, tie-aware.

    Equivalent to the reference's per-query ``binary_auroc`` (exact ROC
    trapezoid) via the Mann-Whitney statistic with tie-averaged ranks.
    """
    length = ranked_target.shape[-1]
    mask = _topk_mask(counts, top_k, length)
    k = jnp.sum(mask, axis=-1, keepdims=True).astype(jnp.float32)  # selected docs per query

    # tie-averaged ascending rank of each selected doc's score
    gid = _row_segment_ids(ranked_preds)
    seg_sum = jax.vmap(partial(jax.ops.segment_sum, num_segments=length))
    # restrict tie groups to the selection: group size/min-position among selected only.
    sel = mask.astype(jnp.float32)
    group_c = seg_sum(sel, gid)
    group_start = jax.vmap(partial(jax.ops.segment_min, num_segments=length))(
        jnp.where(mask, jnp.arange(length)[None, :], length), gid
    ).astype(jnp.float32)
    # descending positions [start, start+c) -> ascending 1-based ranks average
    group_avg_asc = k - group_start - (group_c - 1.0) / 2.0
    avg_rank = jnp.take_along_axis(group_avg_asc, gid, axis=-1)  # (Q, L)

    t = ranked_target * sel
    npos = jnp.sum(t, axis=-1)
    nneg = jnp.sum(sel, axis=-1) - npos
    u = jnp.sum(t * avg_rank, axis=-1) - npos * (npos + 1.0) / 2.0
    return _safe_divide(u, npos * nneg)


def precision_recall_curve_padded(
    ranked_target: Array, counts: Array, max_k: int, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Per-query precision@k / recall@k for k = 1..max_k.

    Reference functional/retrieval/precision_recall_curve.py: cumulative hits
    over ranks, divided by k (precision; with adaptive_k the per-query document
    count caps k) and by the relevant count (recall).
    """
    length = ranked_target.shape[-1]
    pos = jnp.arange(length)[None, :]
    valid = pos < counts[:, None]
    t = ranked_target * valid
    cum = jnp.cumsum(t, axis=-1)
    # hits at k = cum[min(k, n) - 1]
    ks = jnp.arange(1, max_k + 1)[None, :]  # (1, max_k)
    idx = jnp.minimum(ks, counts[:, None]) - 1  # (Q, max_k)
    hits = jnp.take_along_axis(cum, jnp.minimum(idx, length - 1), axis=-1)
    total = jnp.sum(t, axis=-1, keepdims=True)
    recall = _safe_divide(hits, total)
    if adaptive_k:
        topk = jnp.minimum(ks, counts[:, None]).astype(jnp.float32)
    else:
        topk = jnp.broadcast_to(ks, hits.shape).astype(jnp.float32)
    precision = _safe_divide(hits, topk)
    return precision, recall, jnp.arange(1, max_k + 1)
