"""Single-query retrieval functionals (reference functional/retrieval/*.py).

Each takes 1-D ``preds``/``target`` for ONE query, mirroring the reference API;
all delegate to the padded grid kernels with a single row (so the functional
and modular paths share one implementation).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.retrieval._padded import (
    auroc_padded,
    average_precision_padded,
    fall_out_padded,
    hit_rate_padded,
    ndcg_padded,
    precision_padded,
    precision_recall_curve_padded,
    r_precision_padded,
    rank_by_preds,
    recall_padded,
    reciprocal_rank_padded,
)


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Validate one query's inputs (reference utilities/checks.py:553-582)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.size == 0 or preds.ndim == 0:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target and bool(jnp.any((target != 0) & (target != 1))):
        raise ValueError("`target` must contain binary values")
    return preds.astype(jnp.float32).reshape(-1), target.astype(jnp.float32).reshape(-1)


def _check_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


def _one_row(preds: Array, target: Array):
    preds_pad = preds[None, :]
    target_pad = target[None, :]
    counts = jnp.asarray([preds.shape[0]], dtype=jnp.int32)
    ranked_preds, ranked_target = rank_by_preds(preds_pad, target_pad)
    return ranked_preds, ranked_target, counts


def retrieval_precision(
    preds: Array, target: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """retrieval precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_precision(preds, target)
        >>> round(float(result), 4)
        0.4
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    _check_top_k(top_k)
    _, ranked_target, counts = _one_row(preds, target)
    return precision_padded(ranked_target, counts, top_k, adaptive_k)[0]


def retrieval_recall(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """retrieval recall (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_recall
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_recall(preds, target)
        >>> round(float(result), 4)
        1.0
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _check_top_k(top_k)
    _, ranked_target, counts = _one_row(preds, target)
    return recall_padded(ranked_target, counts, top_k)[0]


def retrieval_fall_out(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """retrieval fall out (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_fall_out
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_fall_out(preds, target)
        >>> round(float(result), 4)
        1.0
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _check_top_k(top_k)
    _, ranked_target, counts = _one_row(preds, target)
    return fall_out_padded(ranked_target, counts, top_k)[0]


def retrieval_hit_rate(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """retrieval hit rate (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_hit_rate
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_hit_rate(preds, target)
        >>> round(float(result), 4)
        1.0
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _check_top_k(top_k)
    _, ranked_target, counts = _one_row(preds, target)
    return hit_rate_padded(ranked_target, counts, top_k)[0]


def retrieval_average_precision(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """retrieval average precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_average_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_average_precision(preds, target)
        >>> round(float(result), 4)
        0.8333
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _check_top_k(top_k)
    _, ranked_target, counts = _one_row(preds, target)
    return average_precision_padded(ranked_target, counts, top_k)[0]


def retrieval_reciprocal_rank(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """retrieval reciprocal rank (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_reciprocal_rank
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_reciprocal_rank(preds, target)
        >>> round(float(result), 4)
        1.0
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _check_top_k(top_k)
    _, ranked_target, counts = _one_row(preds, target)
    return reciprocal_rank_padded(ranked_target, counts, top_k)[0]


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """retrieval r precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_r_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_r_precision(preds, target)
        >>> round(float(result), 4)
        0.5
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _, ranked_target, counts = _one_row(preds, target)
    return r_precision_padded(ranked_target, counts)[0]


def retrieval_normalized_dcg(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """retrieval normalized dcg (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_normalized_dcg
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_normalized_dcg(preds, target)
        >>> round(float(result), 4)
        0.9599
    """

    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    _check_top_k(top_k)
    ranked_preds, ranked_target, counts = _one_row(preds, target)
    return ndcg_padded(ranked_preds, ranked_target, counts, top_k)[0]


def retrieval_auroc(
    preds: Array, target: Array, top_k: Optional[int] = None, max_fpr: Optional[float] = None
) -> Array:
    """retrieval auroc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_auroc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_auroc(preds, target)
        >>> round(float(result), 4)
        0.9167
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    _check_top_k(top_k)
    if max_fpr is not None:
        if not isinstance(max_fpr, float) or not 0 < max_fpr <= 1:
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        # partial AUC needs the full ROC curve; reuse the classification kernel
        from torchmetrics_tpu.functional.classification.auroc import binary_auroc

        k = preds.shape[0] if top_k is None else min(top_k, preds.shape[0])
        order = jnp.argsort(-preds, stable=True)[:k]
        return binary_auroc(preds[order], target[order].astype(jnp.int32), max_fpr=max_fpr)
    ranked_preds, ranked_target, counts = _one_row(preds, target)
    return auroc_padded(ranked_preds, ranked_target, counts, top_k)[0]


def retrieval_precision_recall_curve(
    preds: Array, target: Array, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """retrieval precision recall curve (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import retrieval_precision_recall_curve
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> result = retrieval_precision_recall_curve(preds, target)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [[1.0, 0.5, 0.666700005531311, 0.5, 0.3999999761581421], [0.5, 0.5, 1.0, 1.0, 1.0], [1, 2, 3, 4, 5]]
    """

    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if max_k is None:
        max_k = preds.shape[-1]
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")
    _, ranked_target, counts = _one_row(preds, target)
    precision, recall, topk = precision_recall_curve_padded(ranked_target, counts, max_k, adaptive_k)
    if adaptive_k and max_k > preds.shape[-1]:
        topk = jnp.clip(topk, None, preds.shape[-1])
    return precision[0], recall[0], topk
