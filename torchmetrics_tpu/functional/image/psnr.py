"""PSNR (reference functional/image/psnr.py) and PSNR-B (psnrb.py)."""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.parallel.sync import reduce
from torchmetrics_tpu.utils.checks import _check_same_shape


def _psnr_update(
    preds: Array,
    target: Array,
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Tuple[Array, Array]:
    """Sum of squared errors + observation count (reference psnr.py:71-100)."""
    if dim is None:
        sum_squared_error = ((preds - target) ** 2).sum()
        num_obs = jnp.asarray(target.size, dtype=jnp.float32)
        return sum_squared_error, num_obs
    diff = preds - target
    sum_squared_error = (diff * diff).sum(axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    num_obs = jnp.asarray(
        jnp.prod(jnp.asarray([target.shape[d] for d in dim_list])), dtype=jnp.float32
    )
    num_obs = jnp.broadcast_to(num_obs, sum_squared_error.shape)
    return sum_squared_error, num_obs


def _psnr_compute(
    sum_squared_error: Array,
    num_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: str = "elementwise_mean",
) -> Array:
    """PSNR from sse/count (reference psnr.py:24-52)."""
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / num_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(base))
    return reduce(psnr_vals, reduction)


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Union[float, Tuple[float, float], None] = None,
    base: float = 10.0,
    reduction: str = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """Compute PSNR (reference psnr.py:103-161).

    Example:
        >>> from torchmetrics_tpu.functional import peak_signal_noise_ratio
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = peak_signal_noise_ratio(preds, target)
        >>> round(float(result), 4)
        14.322
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if dim is None and reduction != "elementwise_mean":
        from torchmetrics_tpu.utils.prints import rank_zero_warn

        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = target.max() - target.min()  # reference psnr.py: target range only
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = jnp.asarray(data_range[1] - data_range[0], dtype=jnp.float32)
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, num_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, num_obs, data_range, base=base, reduction=reduction)


# ------------------------------------------------------------------- PSNR-B

def _compute_bef(x: Array, block_size: int = 8) -> Array:
    """Blocking effect factor of a (B, 1, H, W) grayscale image (reference psnrb.py:24-66)."""
    if x.shape[1] > 1:
        raise ValueError(f"`psnrb` metric expects grayscale images, but got images with {x.shape[1]} channels.")
    height, width = x.shape[2], x.shape[3]
    h = jnp.arange(width - 1)
    h_b = jnp.arange(block_size - 1, width - 1, block_size)
    mask = jnp.zeros(width - 1, dtype=bool).at[h_b].set(True)
    v = jnp.arange(height - 1)
    v_b = jnp.arange(block_size - 1, height - 1, block_size)
    vmask = jnp.zeros(height - 1, dtype=bool).at[v_b].set(True)

    d_b = ((x[:, :, :, :-1] - x[:, :, :, 1:]) ** 2 * mask[None, None, None, :]).sum()
    d_bc = ((x[:, :, :, :-1] - x[:, :, :, 1:]) ** 2 * (~mask)[None, None, None, :]).sum()
    d_b = d_b + ((x[:, :, :-1, :] - x[:, :, 1:, :]) ** 2 * vmask[None, None, :, None]).sum()
    d_bc = d_bc + ((x[:, :, :-1, :] - x[:, :, 1:, :]) ** 2 * (~vmask)[None, None, :, None]).sum()

    n_hb = height * (width / block_size) - 1
    n_hbc = (height * (width - 1)) - n_hb
    n_vb = width * (height / block_size) - 1
    n_vbc = (width * (height - 1)) - n_vb
    d_b = d_b / (n_hb + n_vb)
    d_bc = d_bc / (n_hbc + n_vbc)
    t = jnp.log2(block_size) / jnp.log2(min(height, width))
    return jnp.where(d_b > d_bc, t * (d_b - d_bc), 0.0)


def peak_signal_noise_ratio_with_blocked_effect(
    preds: Array,
    target: Array,
    block_size: int = 8,
) -> Array:
    """PSNR-B: PSNR with blocking-effect penalty (reference psnrb.py:69-109).

    Example:
        >>> from torchmetrics_tpu.functional import peak_signal_noise_ratio_with_blocked_effect
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(1 * 1 * 32 * 32).reshape(1, 1, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = peak_signal_noise_ratio_with_blocked_effect(preds, target)
        >>> round(float(result), 4)
        7.5802
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    data_range = target.max() - target.min()
    sum_squared_error = ((preds - target) ** 2).sum()
    bef = _compute_bef(preds, block_size=block_size)
    num_obs = jnp.asarray(target.size, dtype=jnp.float32)
    sum_squared_error = sum_squared_error / num_obs + bef
    # reference psnrb.py:83-86: unit-range images use 1.0 as the peak
    return jnp.where(
        data_range > 2,
        10 * jnp.log10(data_range**2 / sum_squared_error),
        10 * jnp.log10(1.0 / sum_squared_error),
    )
