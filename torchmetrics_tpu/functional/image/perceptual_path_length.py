"""Perceptual Path Length (reference functional/image/perceptual_path_length.py:27-284).

PPL = E[ D(G(I(z1, z2, t)), G(I(z1, z2, t+eps))) / eps² ] with D an LPIPS-style
similarity. The generator is a user hook (JAX has no nn.Module): any object
with ``sample(key, num_samples) -> (N, z)`` and ``__call__(z) -> (N, C, H, W)``
images in [0, 255] (plus ``num_classes`` and ``__call__(z, labels)`` when
``conditional=True``). Randomness is explicit via a PRNG key instead of global
torch RNG state.
"""
from __future__ import annotations

import math
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array


class GeneratorType:
    """Interface stub for generator models (reference perceptual_path_length.py:27-47).

    Subclassing is optional — any object with the right methods works.
    """

    @property
    def num_classes(self) -> int:
        raise NotImplementedError

    def sample(self, key: Array, num_samples: int) -> Array:
        """Return ``(num_samples, z_size)`` latents."""
        raise NotImplementedError


def _validate_generator_model(generator, conditional: bool = False) -> None:
    """Reference perceptual_path_length.py:50-69, adapted to the key-taking sample hook."""
    if not hasattr(generator, "sample"):
        raise NotImplementedError(
            "The generator must have a `sample` method with signature `sample(key, num_samples: int) -> Array` where"
            " the returned array has shape `(num_samples, z_size)`."
        )
    if not callable(generator.sample):
        raise ValueError("The generator's `sample` method must be callable.")
    if conditional and not hasattr(generator, "num_classes"):
        raise AttributeError("The generator must have a `num_classes` attribute when `conditional=True`.")
    if conditional and not isinstance(generator.num_classes, int):
        raise ValueError("The generator's `num_classes` attribute must be an integer when `conditional=True`.")


def _perceptual_path_length_validate_arguments(
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 128,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    resize: Optional[int] = 64,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
) -> None:
    """Reference perceptual_path_length.py:72-106."""
    if not (isinstance(num_samples, int) and num_samples > 0):
        raise ValueError(f"Argument `num_samples` must be a positive integer, but got {num_samples}.")
    if not isinstance(conditional, bool):
        raise ValueError(f"Argument `conditional` must be a boolean, but got {conditional}.")
    if not (isinstance(batch_size, int) and batch_size > 0):
        raise ValueError(f"Argument `batch_size` must be a positive integer, but got {batch_size}.")
    if interpolation_method not in ["lerp", "slerp_any", "slerp_unit"]:
        raise ValueError(
            f"Argument `interpolation_method` must be one of 'lerp', 'slerp_any', 'slerp_unit',"
            f"got {interpolation_method}."
        )
    if not (isinstance(epsilon, float) and epsilon > 0):
        raise ValueError(f"Argument `epsilon` must be a positive float, but got {epsilon}.")
    if resize is not None and not (isinstance(resize, int) and resize > 0):
        raise ValueError(f"Argument `resize` must be a positive integer or `None`, but got {resize}.")
    if lower_discard is not None and not (isinstance(lower_discard, float) and 0 <= lower_discard <= 1):
        raise ValueError(
            f"Argument `lower_discard` must be a float between 0 and 1 or `None`, but got {lower_discard}."
        )
    if upper_discard is not None and not (isinstance(upper_discard, float) and 0 <= upper_discard <= 1):
        raise ValueError(
            f"Argument `upper_discard` must be a float between 0 and 1 or `None`, but got {upper_discard}."
        )


def _interpolate(
    latents1: Array,
    latents2: Array,
    epsilon: float = 1e-4,
    interpolation_method: str = "lerp",
) -> Array:
    """Latent interpolation (reference perceptual_path_length.py:109-152), branch-free slerp."""
    eps = 1e-7
    if latents1.shape != latents2.shape:
        raise ValueError("Latents must have the same shape.")
    if interpolation_method == "lerp":
        return latents1 + (latents2 - latents1) * epsilon
    if interpolation_method in ("slerp_any", "slerp_unit"):
        latents1_norm = latents1 / jnp.clip(jnp.sqrt((latents1**2).sum(-1, keepdims=True)), eps)
        latents2_norm = latents2 / jnp.clip(jnp.sqrt((latents2**2).sum(-1, keepdims=True)), eps)
        d = (latents1_norm * latents2_norm).sum(-1, keepdims=True)
        mask_zero = (jnp.linalg.norm(latents1_norm, axis=-1, keepdims=True) < eps) | (
            jnp.linalg.norm(latents2_norm, axis=-1, keepdims=True) < eps
        )
        mask_collinear = (d > 1 - eps) | (d < -1 + eps)
        mask_lerp = mask_zero | mask_collinear
        omega = jnp.arccos(jnp.clip(d, -1.0, 1.0))
        denom = jnp.clip(jnp.sin(omega), eps)
        coef_latents1 = jnp.sin((1 - epsilon) * omega) / denom
        coef_latents2 = jnp.sin(epsilon * omega) / denom
        out = coef_latents1 * latents1 + coef_latents2 * latents2
        lerped = latents1 + (latents2 - latents1) * epsilon
        out = jnp.where(mask_lerp, lerped, out)
        if interpolation_method == "slerp_unit":
            out = out / jnp.clip(jnp.sqrt((out**2).sum(-1, keepdims=True)), eps)
        return out
    raise ValueError(
        f"Interpolation method {interpolation_method} not supported. Choose from 'lerp', 'slerp_any', 'slerp_unit'."
    )


def _area_resize_matrix(in_size: int, out_size: int, dtype) -> Array:
    """Row-stochastic averaging matrix reproducing torch's adaptive/area resize."""
    mat = np.zeros((out_size, in_size), dtype=np.float32)
    for i in range(out_size):
        start = int(math.floor(i * in_size / out_size))
        end = int(math.ceil((i + 1) * in_size / out_size))
        mat[i, start:end] = 1.0 / (end - start)
    return jnp.asarray(mat, dtype=dtype)


def _resize_tensor(x: Array, size: int = 64) -> Array:
    """Reference lpips.py:222-226: area-downsample when larger, else bilinear."""
    n, c, h, w = x.shape
    if h > size and w > size:
        wh = _area_resize_matrix(h, size, x.dtype)
        ww = _area_resize_matrix(w, size, x.dtype)
        return jnp.einsum("oh,nchw,pw->ncop", wh, x, ww)
    return jax.image.resize(x, (n, c, size, size), method="linear")


def perceptual_path_length(
    generator,
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 64,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    resize: Optional[int] = 64,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
    sim_net: Union[Callable[[Array, Array], Array], str, None] = None,
    sim_params=None,
    key: Optional[Array] = None,
) -> Tuple[Array, Array, Array]:
    """Perceptual path length of a generator (reference perceptual_path_length.py:155-284).

    ``sim_net``: a callable ``(img1, img2) -> (N,)`` on [-1, 1] inputs, or a
    net_type string building the flax LPIPS network from ``sim_params``.
    """
    _perceptual_path_length_validate_arguments(
        num_samples, conditional, batch_size, interpolation_method, epsilon, resize, lower_discard, upper_discard
    )
    _validate_generator_model(generator, conditional)
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2, klabels = jax.random.split(key, 3)

    latent1 = jnp.asarray(generator.sample(k1, num_samples))
    latent2 = jnp.asarray(generator.sample(k2, num_samples))
    latent2 = _interpolate(latent1, latent2, epsilon, interpolation_method=interpolation_method)

    if conditional:
        labels = jax.random.randint(klabels, (num_samples,), 0, generator.num_classes)

    if callable(sim_net):
        net = sim_net
    elif sim_net in ("alex", "vgg", "squeeze") or sim_net is None:
        if sim_params is None:
            raise ModuleNotFoundError(
                "perceptual_path_length with a net_type string requires `sim_params` for the built-in"
                " flax LPIPS backbone — pretrained torchvision weights are not bundled. Build params via"
                " models.lpips.init_lpips_params or params_from_torch_state_dict, or pass a callable"
                " `sim_net`."
            )
        from torchmetrics_tpu.models.lpips import lpips_network

        base_net = lpips_network(sim_net or "vgg", sim_params)

        def net(img1: Array, img2: Array) -> Array:
            if resize is not None:
                img1, img2 = _resize_tensor(img1, resize), _resize_tensor(img2, resize)
            return base_net(img1, img2)

    else:
        raise ValueError(f"sim_net must be a callable or one of 'alex', 'vgg', 'squeeze', got {sim_net}")

    distances = []
    num_batches = math.ceil(num_samples / batch_size)
    for batch_idx in range(num_batches):
        batch_latent1 = latent1[batch_idx * batch_size : (batch_idx + 1) * batch_size]
        batch_latent2 = latent2[batch_idx * batch_size : (batch_idx + 1) * batch_size]

        if conditional:
            batch_labels = labels[batch_idx * batch_size : (batch_idx + 1) * batch_size]
            outputs = generator(
                jnp.concatenate((batch_latent1, batch_latent2), axis=0),
                jnp.concatenate((batch_labels, batch_labels), axis=0),
            )
        else:
            outputs = generator(jnp.concatenate((batch_latent1, batch_latent2), axis=0))

        out1, out2 = jnp.split(outputs, 2, axis=0)
        # rescale to lpips expected domain: [0, 255] -> [0, 1] -> [-1, 1]
        out1_rescale = 2 * (out1 / 255) - 1
        out2_rescale = 2 * (out2 / 255) - 1

        similarity = jnp.asarray(net(out1_rescale, out2_rescale))
        distances.append(similarity.reshape(-1) / epsilon**2)

    dists = jnp.concatenate(distances)

    lower = jnp.quantile(dists, lower_discard, method="lower") if lower_discard is not None else 0.0
    upper = jnp.quantile(dists, upper_discard, method="lower") if upper_discard is not None else dists.max()
    keep = (dists >= lower) & (dists <= upper)
    kept = dists[keep]

    return kept.mean(), kept.std(ddof=1), kept
