"""Finite-difference image gradients.

Reference: functional/image/gradients.py:20-80 — 1-step finite difference
(TF-style): dy[x, y] = I(x+1, y) - I(x, y) with a zero last row; dx likewise
with a zero last column. Implemented with jnp.pad instead of cat-of-zeros so
XLA fuses the whole thing into one elementwise kernel.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array


def _image_gradients_validate(img: Array) -> None:
    """Validate that ``img`` is a 4D array (reference gradients.py:20-25)."""
    if not hasattr(img, "ndim"):
        raise TypeError(f"The `img` expects an array type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    """Per-pixel forward differences, zero-padded on the trailing edge."""
    dy = jnp.pad(img[..., 1:, :] - img[..., :-1, :], ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(img[..., :, 1:] - img[..., :, :-1], ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """Compute gradients ``(dy, dx)`` of an ``(N, C, H, W)`` image batch.

    Reference: functional/image/gradients.py:46-80.

    Example:
        >>> from torchmetrics_tpu.functional import image_gradients
        >>> import jax.numpy as jnp
        >>> img = jnp.arange(1 * 1 * 4 * 4, dtype=jnp.float32).reshape(1, 1, 4, 4)
        >>> result = image_gradients(img)
        >>> [v.shape for v in result]
        [(1, 1, 4, 4), (1, 1, 4, 4)]
    """
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
