"""Learned Perceptual Image Patch Similarity (LPIPS) — score math.

Reference: functional/image/lpips.py:205-435 (NoTrainLpips forward + update/
compute). The score pipeline is re-expressed as a pure function over a
pluggable *feature stack*:

    score(x, y) = sum_k spatial_mean( w_k · (nhat_k(x) - nhat_k(y))**2 )

where ``nhat_k`` is the channel-unit-normalised k-th backbone activation
(reference ``_normalize_tensor``, lpips.py:215-219) and ``w_k`` is the 1x1
"lin" convolution collapsed to a per-channel weight vector (reference
``NetLinLayer``, lpips.py:242-257 — a bias-free 1x1 conv to one channel is
exactly a weighted channel sum).

The backbone is a callable ``img -> sequence of (N, C_k, H_k, W_k) feature
maps``; architecture-faithful flax backbones (alex/vgg/squeeze) live in
``torchmetrics_tpu.models.lpips``. This keeps the hot path — convs + one
fused elementwise chain per layer — entirely inside XLA.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _is_concrete

# ImageNet-statistics scaling layer (reference lpips.py:228-239).
_SHIFT = (-0.030, -0.088, -0.188)
_SCALE = (0.458, 0.448, 0.450)


def _normalize_tensor(feat: Array, eps: float = 1e-8) -> Array:
    """Unit-normalise over the channel axis (reference lpips.py:215-219)."""
    norm_factor = jnp.sqrt(eps + jnp.sum(feat**2, axis=1, keepdims=True))
    return feat / norm_factor


def _spatial_average(x: Array) -> Array:
    """Mean over H, W keeping dims (reference lpips.py:205-208)."""
    return x.mean(axis=(2, 3), keepdims=True)


def _scaling_layer(img: Array) -> Array:
    shift = jnp.asarray(_SHIFT, dtype=img.dtype)[None, :, None, None]
    scale = jnp.asarray(_SCALE, dtype=img.dtype)[None, :, None, None]
    return (img - shift) / scale


def _valid_img(img: Array, normalize: bool) -> bool:
    """Range/shape check (reference lpips.py:380-383); range only when concrete."""
    if img.ndim != 4 or img.shape[1] != 3:
        return False
    if not _is_concrete(img):
        return True
    if normalize:
        return bool(img.max() <= 1.0 and img.min() >= 0.0)
    return bool(img.min() >= -1.0)


def _lpips_score(
    img1: Array,
    img2: Array,
    feature_stack: Callable[[Array], Sequence[Array]],
    lin_weights: Optional[Sequence[Array]] = None,
    normalize: bool = False,
) -> Array:
    """Per-sample LPIPS scores ``(N,)`` (reference _LPIPS.forward, lpips.py:338-369)."""
    if normalize:  # [0,1] -> [-1,1]
        img1 = 2 * img1 - 1
        img2 = 2 * img2 - 1
    in0, in1 = _scaling_layer(img1), _scaling_layer(img2)
    outs0, outs1 = feature_stack(in0), feature_stack(in1)
    if lin_weights is None:
        lin_weights = [None] * len(outs0)
    if len(lin_weights) != len(outs0):
        raise ValueError(
            f"Got {len(lin_weights)} lin weights for a {len(outs0)}-layer feature stack."
        )
    total = None
    for f0, f1, w in zip(outs0, outs1, lin_weights):
        diff = (_normalize_tensor(f0) - _normalize_tensor(f1)) ** 2
        if w is None:  # unweighted: plain channel mean-free sum, as lin with ones
            layer = diff.sum(axis=1, keepdims=True)
        else:
            w = jnp.asarray(w, dtype=diff.dtype).reshape(1, -1, 1, 1)
            layer = (diff * w).sum(axis=1, keepdims=True)
        layer = _spatial_average(layer)
        total = layer if total is None else total + layer
    return total.reshape(total.shape[0])


def _lpips_update(
    img1: Array,
    img2: Array,
    net: Callable[[Array, Array], Array],
    normalize: bool,
) -> Tuple[Array, Union[int, Array]]:
    """Validate inputs, score the batch (reference lpips.py:386-396)."""
    if not (_valid_img(img1, normalize) and _valid_img(img2, normalize)):
        raise ValueError(
            "Expected both input arguments to be normalized tensors with shape [N, 3, H, W]."
            f" Got input with shape {img1.shape} and {img2.shape} and values outside the"
            f" expected {[0, 1] if normalize else [-1, 1]} range."
        )
    if normalize:  # hook contract: `net` always sees [-1, 1] inputs
        img1 = 2 * jnp.asarray(img1) - 1
        img2 = 2 * jnp.asarray(img2) - 1
    loss = jnp.asarray(net(img1, img2)).reshape(img1.shape[0])
    return loss, img1.shape[0]


def _lpips_compute(sum_scores: Array, total: Union[Array, int], reduction: str = "mean") -> Array:
    return sum_scores / total if reduction == "mean" else sum_scores


def learned_perceptual_image_patch_similarity(
    img1: Array,
    img2: Array,
    net: Optional[Callable[[Array, Array], Array]] = None,
    reduction: str = "mean",
    normalize: bool = False,
) -> Array:
    """LPIPS between two image batches (reference lpips.py:399-435).

    Unlike the reference (which downloads torchvision backbones), the scoring
    network is explicit: ``net(img1, img2) -> (N,)`` per-sample scores with
    inputs in [-1, 1]. Build one with
    :func:`torchmetrics_tpu.models.lpips.lpips_network` (flax alex/vgg/squeeze
    backbones + lin heads) or pass any callable.

    Example:
        >>> from torchmetrics_tpu.functional import learned_perceptual_image_patch_similarity
        >>> import jax.numpy as jnp
        >>> img1 = (jnp.arange(4 * 3 * 8 * 8).reshape(4, 3, 8, 8) % 255) / 255.0
        >>> img2 = img1 * 0.7
        >>> result = learned_perceptual_image_patch_similarity(img1, img2, net=lambda a, b: jnp.mean((a - b) ** 2, axis=(1, 2, 3)))
        >>> round(float(result), 4)
        0.0297
    """
    if net is None:
        raise ModuleNotFoundError(
            "learned_perceptual_image_patch_similarity requires a `net` callable"
            " (img1, img2) -> (N,) scores; pretrained torchvision backbones are not"
            " bundled. Build one via torchmetrics_tpu.models.lpips.lpips_network."
        )
    if reduction not in ("mean", "sum"):
        raise ValueError(f"Argument `reduction` must be one of ['mean', 'sum'], got {reduction}")
    img1, img2 = jnp.asarray(img1), jnp.asarray(img2)
    loss, total_count = _lpips_update(img1, img2, net, normalize)
    return _lpips_compute(loss.sum(), total_count, reduction)
