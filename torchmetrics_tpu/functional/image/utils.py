"""Image-kernel utilities (reference functional/image/utils.py).

Gaussian/uniform separable kernels and scipy-compatible reflection padding,
expressed with lax.conv_general_dilated (NCHW / OIHW) — grouped convs map onto
the TPU's convolution units directly.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array, lax


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1-D gaussian kernel (reference utils.py:8-24)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1, dtype=dtype)
    gauss = jnp.exp(-jnp.power(dist / sigma, 2) / 2)
    return (gauss / gauss.sum())[None]  # (1, kernel_size)


def _gaussian_kernel_2d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32
) -> Array:
    """(C, 1, kh, kw) separable gaussian kernel (reference utils.py:27-56)."""
    gaussian_kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    gaussian_kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = jnp.matmul(gaussian_kernel_x.T, gaussian_kernel_y)  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32
) -> Array:
    """(C, 1, kd, kh, kw) 3-D gaussian kernel (reference utils.py:135-156)."""
    gaussian_kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    gaussian_kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    gaussian_kernel_z = _gaussian(kernel_size[2], sigma[2], dtype)
    kernel_xy = jnp.matmul(gaussian_kernel_x.T, gaussian_kernel_y)  # (kh, kw)
    kernel = kernel_xy[None] * gaussian_kernel_z.reshape(-1, 1, 1)  # (kd, kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _conv2d_grouped(x: Array, kernel: Array) -> Array:
    """Per-channel (grouped) valid conv, NCHW x (C,1,kh,kw)."""
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=x.shape[1],
    )


def _conv2d(x: Array, kernel: Array) -> Array:
    """Plain valid conv, NCHW x (O,I,kh,kw)."""
    return lax.conv_general_dilated(
        x, kernel, window_strides=(1, 1), padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )


def _reflect_pad_2d(x: Array, pad_h: int, pad_w: int) -> Array:
    """Reflect padding (torch 'reflect' mode: edge not repeated)."""
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _single_dimension_pad(inputs: Array, dim: int, pad: int, outer_pad: int = 0) -> Array:
    """Scipy-style symmetric padding over one dim (reference utils.py:76-92)."""
    _max = inputs.shape[dim]
    x = jnp.take(inputs, jnp.arange(pad - 1, -1, -1), axis=dim)
    y = jnp.take(inputs, jnp.arange(_max - 1, _max - pad - outer_pad, -1), axis=dim)
    return jnp.concatenate((x, inputs, y), axis=dim)


def _reflection_pad_2d(inputs: Array, pad: int, outer_pad: int = 0) -> Array:
    """Symmetric pad over H and W (reference utils.py:95-109)."""
    for dim in (2, 3):
        inputs = _single_dimension_pad(inputs, dim, pad, outer_pad)
    return inputs


def _uniform_filter(inputs: Array, window_size: int) -> Array:
    """Uniform (box) filter with scipy-compatible padding (reference utils.py:112-132)."""
    inputs = _reflection_pad_2d(inputs, window_size // 2, window_size % 2)
    kernel = jnp.ones((inputs.shape[1], 1, window_size, window_size), dtype=inputs.dtype) / (window_size**2)
    return _conv2d_grouped(inputs, kernel)


def _conv3d_grouped(x: Array, kernel: Array) -> Array:
    """Per-channel (grouped) valid conv, NCDHW x (C,1,kd,kh,kw)."""
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1, 1),
        padding="VALID",
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=x.shape[1],
    )


def _reflect_pad_3d(x: Array, pad_d: int, pad_h: int, pad_w: int) -> Array:
    return jnp.pad(x, ((0, 0), (0, 0), (pad_d, pad_d), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _avg_pool2d(x: Array, kernel: int = 2) -> Array:
    """Average pooling NCHW (for MS-SSIM downsampling)."""
    return lax.reduce_window(
        x, 0.0, lax.add, (1, 1, kernel, kernel), (1, 1, kernel, kernel), "VALID"
    ) / (kernel * kernel)
