"""Image-kernel utilities (reference functional/image/utils.py).

Gaussian/uniform separable windows and scipy-compatible reflection padding.
Windowed sums dispatch between banded matmuls (GEMM: MXU on TPU, BLAS on CPU)
and 1-D grouped `lax.conv_general_dilated` passes depending on image size —
see `_separable_window_2d`.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array, lax


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1-D gaussian kernel, shape (kernel_size,) (reference utils.py:8-24)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1, dtype=dtype)
    gauss = jnp.exp(-jnp.power(dist / sigma, 2) / 2)
    return gauss / gauss.sum()


def _band_matrix(g: Array, out_len: int) -> Array:
    """(out_len + k - 1, out_len) banded matrix B with B[o + d, o] = g[d].

    ``x_padded @ B`` equals the valid 1-D cross-correlation of ``x_padded``
    with ``g`` — the separable-window trick expressed as a GEMM so it rides the
    MXU on TPU (and BLAS on CPU) instead of XLA's slow small-kernel conv path.
    """
    k = g.shape[0]
    rows = jnp.arange(out_len + k - 1)[:, None]
    cols = jnp.arange(out_len)[None, :]
    d = rows - cols
    return jnp.where((d >= 0) & (d < k), g[jnp.clip(d, 0, k - 1)], jnp.zeros((), dtype=g.dtype))


# Above this edge length the banded matrices' O(H^2) MACs/memory overtake the
# 1-D conv path; below it the GEMM lowering wins on every backend (measured on
# XLA CPU: 17x at 256, still 2.4x at 2048; on TPU the GEMM rides the MXU).
_WINDOW_GEMM_MAX_DIM = 2048


def _grouped_conv1d_axis(x: Array, g: Array, axis: int) -> Array:
    """Valid per-channel conv with 1-D kernel ``g`` along one spatial axis of NCHW/NCDHW."""
    nspatial = x.ndim - 2
    shape = [1, 1] + [1] * nspatial
    shape[axis] = g.shape[0]
    kernel = jnp.broadcast_to(g.reshape(shape), (x.shape[1], 1, *shape[2:]))
    dn = ("NCHW", "OIHW", "NCHW") if nspatial == 2 else ("NCDHW", "OIDHW", "NCDHW")
    return lax.conv_general_dilated(
        x, kernel, window_strides=(1,) * nspatial, padding="VALID",
        dimension_numbers=dn, feature_group_count=x.shape[1],
    )


def _separable_window_2d(x: Array, g_h: Array, g_w: Array) -> Array:
    """Valid separable windowed sum of NCHW ``x`` (≡ per-channel VALID conv with
    the rank-1 kernel ``outer(g_h, g_w)``), k²→2k MACs vs the dense kernel.

    Dispatch: banded matmuls (`_band_matrix`) up to `_WINDOW_GEMM_MAX_DIM` —
    a GEMM lowering that is MXU-tiled on TPU and BLAS-backed on CPU, far faster
    than XLA's small-kernel conv despite costing O(H+W) MACs/pixel — and two
    1-D grouped convs (O(k)/pixel, O(1) extra memory) beyond it.
    """
    if max(x.shape[2], x.shape[3]) > _WINDOW_GEMM_MAX_DIM:
        return _grouped_conv1d_axis(_grouped_conv1d_axis(x, g_h.astype(x.dtype), 2), g_w.astype(x.dtype), 3)
    ho = x.shape[2] - g_h.shape[0] + 1
    wo = x.shape[3] - g_w.shape[0] + 1
    bh = _band_matrix(g_h.astype(x.dtype), ho)  # (Hp, Ho)
    bw = _band_matrix(g_w.astype(x.dtype), wo)  # (Wp, Wo)
    # the contraction pair runs through the ops/kernels.py seam: a fused
    # VMEM-resident Pallas kernel on TPU/GPU, the einsum pair (full-precision
    # passes — windowed moment statistics cannot survive bf16 truncation)
    # as the reference body everywhere else
    from torchmetrics_tpu.ops.ssim_kernel import windowed_sum_2d

    n, c = x.shape[0], x.shape[1]
    out = windowed_sum_2d(x.reshape(n * c, x.shape[2], x.shape[3]), bh, bw)
    return out.reshape(n, c, ho, wo).astype(x.dtype)


def _separable_window_3d(x: Array, g_d: Array, g_h: Array, g_w: Array) -> Array:
    """Valid separable windowed sum of NCDHW ``x``; same dispatch as the 2-D case."""
    if max(x.shape[2:]) > _WINDOW_GEMM_MAX_DIM:
        out = _grouped_conv1d_axis(x, g_d.astype(x.dtype), 2)
        out = _grouped_conv1d_axis(out, g_h.astype(x.dtype), 3)
        return _grouped_conv1d_axis(out, g_w.astype(x.dtype), 4)
    do = x.shape[2] - g_d.shape[0] + 1
    ho = x.shape[3] - g_h.shape[0] + 1
    wo = x.shape[4] - g_w.shape[0] + 1
    bd = _band_matrix(g_d.astype(x.dtype), do)
    bh = _band_matrix(g_h.astype(x.dtype), ho)
    bw = _band_matrix(g_w.astype(x.dtype), wo)
    out = jnp.einsum("ncdhw,de->ncehw", x, bd, precision=lax.Precision.HIGHEST)
    out = jnp.einsum("ncehw,hi->nceiw", out, bh, precision=lax.Precision.HIGHEST)
    return jnp.einsum("nceiw,wj->nceij", out, bw, precision=lax.Precision.HIGHEST)


def _conv2d(x: Array, kernel: Array) -> Array:
    """Plain valid conv, NCHW x (O,I,kh,kw)."""
    return lax.conv_general_dilated(
        x, kernel, window_strides=(1, 1), padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )


def _reflect_pad_2d(x: Array, pad_h: int, pad_w: int) -> Array:
    """Reflect padding (torch 'reflect' mode: edge not repeated)."""
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _single_dimension_pad(inputs: Array, dim: int, pad: int, outer_pad: int = 0) -> Array:
    """Scipy-style symmetric padding over one dim (reference utils.py:76-92)."""
    _max = inputs.shape[dim]
    x = jnp.take(inputs, jnp.arange(pad - 1, -1, -1), axis=dim)
    y = jnp.take(inputs, jnp.arange(_max - 1, _max - pad - outer_pad, -1), axis=dim)
    return jnp.concatenate((x, inputs, y), axis=dim)


def _reflection_pad_2d(inputs: Array, pad: int, outer_pad: int = 0) -> Array:
    """Symmetric pad over H and W (reference utils.py:95-109)."""
    for dim in (2, 3):
        inputs = _single_dimension_pad(inputs, dim, pad, outer_pad)
    return inputs


def _uniform_filter(inputs: Array, window_size: int) -> Array:
    """Uniform (box) filter with scipy-compatible padding (reference utils.py:112-132)."""
    inputs = _reflection_pad_2d(inputs, window_size // 2, window_size % 2)
    uniform = jnp.full((window_size,), 1.0 / window_size, dtype=inputs.dtype)
    return _separable_window_2d(inputs, uniform, uniform)


def _reflect_pad_3d(x: Array, pad_d: int, pad_h: int, pad_w: int) -> Array:
    return jnp.pad(x, ((0, 0), (0, 0), (pad_d, pad_d), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _avg_pool2d(x: Array, kernel: int = 2) -> Array:
    """Average pooling NCHW (for MS-SSIM downsampling)."""
    return lax.reduce_window(
        x, 0.0, lax.add, (1, 1, kernel, kernel), (1, 1, kernel, kernel), "VALID"
    ) / (kernel * kernel)
