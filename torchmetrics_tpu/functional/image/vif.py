"""Pixel-based Visual Information Fidelity (reference functional/image/vif.py)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.utils import _separable_window_2d


def _filter_1d(win_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1-D factor of the separable VIF gaussian; outer(g, g) is the 2-D filter."""
    coords = jnp.arange(win_size, dtype=dtype) - (win_size - 1) / 2
    g = jnp.exp(-(coords**2) / (2.0 * sigma**2))
    return g / g.sum()


def _vif_per_channel(preds: Array, target: Array, sigma_n_sq: float) -> Array:
    """(B, H, W) single-channel VIF over 4 scales (reference vif.py:31-82)."""
    preds = preds[:, None]
    target = target[:, None]
    eps = 1e-10

    preds_vif = jnp.zeros(preds.shape[0])
    target_vif = jnp.zeros(preds.shape[0])
    for scale in range(4):
        n = int(2.0 ** (4 - scale) + 1)
        g1 = _filter_1d(n, n / 5, preds.dtype)

        if scale > 0:
            target = _separable_window_2d(target, g1, g1)[:, :, ::2, ::2]
            preds = _separable_window_2d(preds, g1, g1)[:, :, ::2, ::2]

        mu_target = _separable_window_2d(target, g1, g1)
        mu_preds = _separable_window_2d(preds, g1, g1)
        mu_target_sq = mu_target**2
        mu_preds_sq = mu_preds**2
        mu_target_preds = mu_target * mu_preds

        sigma_target_sq = jnp.clip(_separable_window_2d(target**2, g1, g1) - mu_target_sq, min=0.0)
        sigma_preds_sq = jnp.clip(_separable_window_2d(preds**2, g1, g1) - mu_preds_sq, min=0.0)
        sigma_target_preds = _separable_window_2d(target * preds, g1, g1) - mu_target_preds

        g = sigma_target_preds / (sigma_target_sq + eps)
        sigma_v_sq = sigma_preds_sq - g * sigma_target_preds

        mask = sigma_target_sq < eps
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.where(mask, sigma_preds_sq, sigma_v_sq)
        sigma_target_sq = jnp.where(mask, 0.0, sigma_target_sq)

        mask = sigma_preds_sq < eps
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.where(mask, 0.0, sigma_v_sq)

        mask = g < 0
        sigma_v_sq = jnp.where(mask, sigma_preds_sq, sigma_v_sq)
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.clip(sigma_v_sq, min=eps)

        preds_vif_scale = jnp.log10(1.0 + (g**2.0) * sigma_target_sq / (sigma_v_sq + sigma_n_sq))
        preds_vif = preds_vif + preds_vif_scale.sum(axis=(1, 2, 3))
        target_vif = target_vif + jnp.log10(1.0 + sigma_target_sq / sigma_n_sq).sum(axis=(1, 2, 3))
    return preds_vif / target_vif


def visual_information_fidelity(preds: Array, target: Array, sigma_n_sq: float = 2.0) -> Array:
    """Compute VIF-p (reference vif.py:85+).

    Example:
        >>> from torchmetrics_tpu.functional import visual_information_fidelity
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(1 * 3 * 48 * 48).reshape(1, 3, 48, 48) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = visual_information_fidelity(preds, target)
        >>> round(float(result), 4)
        1.7622
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    if preds.shape[-1] < 41 or preds.shape[-2] < 41:
        raise ValueError(f"Invalid size of preds. Expected at least 41x41, but got {preds.shape[-1]}x{preds.shape[-2]}!")
    if target.shape[-1] < 41 or target.shape[-2] < 41:
        raise ValueError(
            f"Invalid size of target. Expected at least 41x41, but got {target.shape[-1]}x{target.shape[-2]}!"
        )
    per_channel = [
        _vif_per_channel(preds[:, i], target[:, i], sigma_n_sq).mean() for i in range(preds.shape[1])
    ]
    return jnp.stack(per_channel).mean() if len(per_channel) > 1 else per_channel[0]
