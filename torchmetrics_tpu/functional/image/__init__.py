from torchmetrics_tpu.functional.image.misc import (  # noqa: F401
    error_relative_global_dimensionless_synthesis,
    relative_average_spectral_error,
    root_mean_squared_error_using_sliding_window,
    spatial_correlation_coefficient,
    spectral_angle_mapper,
    total_variation,
    universal_image_quality_index,
)
from torchmetrics_tpu.functional.image.psnr import (  # noqa: F401
    peak_signal_noise_ratio,
    peak_signal_noise_ratio_with_blocked_effect,
)
from torchmetrics_tpu.functional.image.ssim import (  # noqa: F401
    multiscale_structural_similarity_index_measure,
    structural_similarity_index_measure,
)
from torchmetrics_tpu.functional.image.pansharpening import (  # noqa: F401
    quality_with_no_reference,
    spatial_distortion_index,
    spectral_distortion_index,
)
from torchmetrics_tpu.functional.image.vif import visual_information_fidelity  # noqa: F401
from torchmetrics_tpu.functional.image.gradients import image_gradients  # noqa: F401
from torchmetrics_tpu.functional.image.lpips import learned_perceptual_image_patch_similarity  # noqa: F401
from torchmetrics_tpu.functional.image.perceptual_path_length import perceptual_path_length  # noqa: F401
