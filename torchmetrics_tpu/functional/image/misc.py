"""Pure-tensor image metrics: TV, UQI, SAM, ERGAS, RMSE-SW, RASE, SCC.

Reference: functional/image/{tv,uqi,sam,ergas,rmse_sw,rase,scc}.py.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.utils import (
    _conv2d,
    _gaussian,
    _reflect_pad_2d,
    _separable_window_2d,
    _uniform_filter,
)
from torchmetrics_tpu.parallel.sync import reduce
from torchmetrics_tpu.utils.checks import _check_same_shape


# ------------------------------------------------------------------------- TV
def _total_variation_update(img: Array) -> Tuple[Array, int]:
    """Per-image anisotropic total variation (reference tv.py:20-40)."""
    if img.ndim != 4:
        raise RuntimeError(f"Expected input `img` to be an 4D tensor, but got {img.shape}")
    diff1 = img[..., 1:, :] - img[..., :-1, :]
    diff2 = img[..., :, 1:] - img[..., :, :-1]
    res1 = jnp.abs(diff1).sum((1, 2, 3))
    res2 = jnp.abs(diff2).sum((1, 2, 3))
    return res1 + res2, img.shape[0]


def total_variation(img: Array, reduction: Optional[str] = "sum") -> Array:
    """Compute total variation (reference tv.py:43-77).

    Example:
        >>> from torchmetrics_tpu.functional import total_variation
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = total_variation(preds)
        >>> round(float(result), 4)
        1288.4155
    """
    score, num_elements = _total_variation_update(jnp.asarray(img, dtype=jnp.float32))
    if reduction == "sum":
        return score.sum()
    if reduction == "mean":
        return score.mean()
    if reduction in ("none", None):
        return score
    raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")


# ------------------------------------------------------------------------ UQI
def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """UQI — SSIM with C1=C2=0 structure (reference uqi.py:84-118).

    Example:
        >>> from torchmetrics_tpu.functional import universal_image_quality_index
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = universal_image_quality_index(preds, target)
        >>> round(float(result), 4)
        0.9216
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    g_h = _gaussian(kernel_size[0], sigma[0], preds.dtype)
    g_w = _gaussian(kernel_size[1], sigma[1], preds.dtype)
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2
    preds_p = _reflect_pad_2d(preds, pad_h, pad_w)
    target_p = _reflect_pad_2d(target, pad_h, pad_w)

    input_list = jnp.concatenate([preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p])
    outputs = _separable_window_2d(input_list, g_h, g_w)
    b = preds.shape[0]
    mu_pred = outputs[:b]
    mu_target = outputs[b : 2 * b]
    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = jnp.clip(outputs[2 * b : 3 * b] - mu_pred_sq, min=0.0)
    sigma_target_sq = jnp.clip(outputs[3 * b : 4 * b] - mu_target_sq, min=0.0)
    sigma_pred_target = outputs[4 * b :] - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq
    eps = jnp.finfo(sigma_pred_sq.dtype).eps
    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower + eps)
    uqi_idx = uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w]
    return reduce(uqi_idx, reduction)


# ------------------------------------------------------------------------ SAM
def spectral_angle_mapper(
    preds: Array,
    target: Array,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Per-pixel spectral angle over the channel axis, radians (reference sam.py).

    Example:
        >>> from torchmetrics_tpu.functional import spectral_angle_mapper
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = spectral_angle_mapper(preds, target)
        >>> round(float(result), 4)
        0.0001
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if preds.shape[1] <= 1:
        raise ValueError(f"Expected channel dimension of `preds` and `target` to be larger than 1. Got preds: {preds.shape[1]}.")
    dot_product = (preds * target).sum(1)
    preds_norm = jnp.linalg.norm(preds, axis=1)
    target_norm = jnp.linalg.norm(target, axis=1)
    sam_score = jnp.clip(dot_product / (preds_norm * target_norm), -1.0, 1.0)
    sam_score = jnp.arccos(sam_score)
    return reduce(sam_score, reduction)


# ---------------------------------------------------------------------- ERGAS
def error_relative_global_dimensionless_synthesis(
    preds: Array,
    target: Array,
    ratio: float = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """ERGAS (reference ergas.py:46-123).

    Example:
        >>> from torchmetrics_tpu.functional import error_relative_global_dimensionless_synthesis
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = error_relative_global_dimensionless_synthesis(preds, target)
        >>> round(float(result), 4)
        9.6476
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    b, c, h, w = preds.shape
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)
    diff = preds - target
    sum_squared_error = (diff * diff).sum(2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = target.mean(2)
    ergas_score = 100 / ratio * jnp.sqrt(((rmse_per_band / mean_target) ** 2).sum(1) / c)
    return reduce(ergas_score, reduction)


# -------------------------------------------------------------------- RMSE-SW
def _rmse_sw_single(preds: Array, target: Array, window_size: int) -> Tuple[Array, Array]:
    """Per-batch (rmse_value, rmse_map-sum) (reference rmse_sw.py:24-87)."""
    error = (target - preds) ** 2
    error = _uniform_filter(error, window_size)
    rmse_map = jnp.sqrt(error)
    crop = round(window_size / 2)
    rmse_val = rmse_map[:, :, crop:-crop, crop:-crop].sum(0).mean()
    return rmse_val, rmse_map


def root_mean_squared_error_using_sliding_window(
    preds: Array,
    target: Array,
    window_size: int = 8,
    return_rmse_map: bool = False,
):
    """Sliding-window RMSE (reference rmse_sw.py:111+).

    Example:
        >>> from torchmetrics_tpu.functional import root_mean_squared_error_using_sliding_window
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = root_mean_squared_error_using_sliding_window(preds, target)
        >>> round(float(result), 4)
        0.1445
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if not isinstance(window_size, int) or (isinstance(window_size, int) and window_size < 1):
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_val, rmse_map = _rmse_sw_single(preds, target, window_size)
    rmse = rmse_val / preds.shape[0]
    rmse_map = rmse_map.sum(0) / preds.shape[0]
    if return_rmse_map:
        return rmse, rmse_map
    return rmse


# ----------------------------------------------------------------------- RASE
def relative_average_spectral_error(preds: Array, target: Array, window_size: int = 8) -> Array:
    """RASE (reference rase.py): 100/μ · RMS of per-band sliding RMSE.

    Example:
        >>> from torchmetrics_tpu.functional import relative_average_spectral_error
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = relative_average_spectral_error(preds, target)
        >>> round(float(result), 4)
        2460.3965
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if not isinstance(window_size, int) or (isinstance(window_size, int) and window_size < 1):
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    _, rmse_map = _rmse_sw_single(preds, target, window_size)
    rmse_map = rmse_map.sum(0) / preds.shape[0]  # (C, H, W)
    target_mean = (_uniform_filter(target, window_size) / (window_size**2)).sum(0) / preds.shape[0]
    target_mean = target_mean.mean(0)  # (H, W) mean over channels
    rase_map = 100 / target_mean * jnp.sqrt((rmse_map**2).mean(0))
    crop = round(window_size / 2)
    return rase_map[crop:-crop, crop:-crop].mean()


# ------------------------------------------------------------------------ SCC
def _symmetric_reflect_pad_2d(x: Array, pad: Tuple[int, int, int, int]) -> Array:
    """Symmetric padding d c b a | a b c d | d c b a (reference scc.py:77-90)."""
    left = jnp.flip(x[:, :, :, 0 : pad[0]], axis=3)
    right = jnp.flip(x[:, :, :, x.shape[3] - pad[1] :], axis=3)
    padded = jnp.concatenate([left, x, right], axis=3)
    top = jnp.flip(padded[:, :, 0 : pad[2], :], axis=2)
    bottom = jnp.flip(padded[:, :, padded.shape[2] - pad[3] :, :], axis=2)
    return jnp.concatenate([top, padded, bottom], axis=2)


def _signal_convolve_2d(x: Array, kernel: Array) -> Array:
    """scipy.signal-style 2D convolution (flip kernel + symmetric pad)."""
    left = int(math.floor((kernel.shape[3] - 1) / 2))
    right = int(math.ceil((kernel.shape[3] - 1) / 2))
    top = int(math.floor((kernel.shape[2] - 1) / 2))
    bottom = int(math.ceil((kernel.shape[2] - 1) / 2))
    padded = _symmetric_reflect_pad_2d(x, (left, right, top, bottom))
    kernel = jnp.flip(kernel, axis=(2, 3))
    return _conv2d(padded, kernel)


def _scc_per_channel(preds: Array, target: Array, hp_filter: Array, window_size: int) -> Array:
    """Per-channel SCC map (reference scc.py:140-165). preds/target are (B,1,H,W)."""
    preds_hp = _signal_convolve_2d(preds, hp_filter) * 2.0
    target_hp = _signal_convolve_2d(target, hp_filter) * 2.0

    left = int(math.ceil((window_size - 1) / 2))
    right = int(math.floor((window_size - 1) / 2))
    pp = jnp.pad(preds_hp, ((0, 0), (0, 0), (left, right), (left, right)))
    tt = jnp.pad(target_hp, ((0, 0), (0, 0), (left, right), (left, right)))
    uniform = jnp.full((window_size,), 1.0 / window_size, dtype=preds.dtype)
    preds_mean = _separable_window_2d(pp, uniform, uniform)
    target_mean = _separable_window_2d(tt, uniform, uniform)
    preds_var = _separable_window_2d(pp**2, uniform, uniform) - preds_mean**2
    target_var = _separable_window_2d(tt**2, uniform, uniform) - target_mean**2
    cov = _separable_window_2d(tt * pp, uniform, uniform) - target_mean * preds_mean

    preds_var = jnp.clip(preds_var, min=0.0)
    target_var = jnp.clip(target_var, min=0.0)
    den = jnp.sqrt(target_var) * jnp.sqrt(preds_var)
    scc = jnp.where(den == 0, 0.0, cov / jnp.where(den == 0, 1.0, den))
    return scc


def spatial_correlation_coefficient(
    preds: Array,
    target: Array,
    hp_filter: Optional[Array] = None,
    window_size: int = 8,
    reduction: Optional[str] = "mean",
) -> Array:
    """SCC (reference scc.py:169+).

    Example:
        >>> from torchmetrics_tpu.functional import spatial_correlation_coefficient
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = spatial_correlation_coefficient(preds, target)
        >>> round(float(result), 4)
        1.0
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    if hp_filter is None:
        hp_filter = jnp.asarray([[-1.0, -1.0, -1.0], [-1.0, 8.0, -1.0], [-1.0, -1.0, -1.0]])
    if preds.ndim == 3:
        preds = preds[:, None]
        target = target[:, None]
    _check_same_shape(preds, target)
    if not window_size > 0:
        raise ValueError(f"Expected `window_size` to be a positive integer. Got {window_size}.")
    if window_size > preds.shape[2] or window_size > preds.shape[3]:
        raise ValueError(
            f"Expected `window_size` to be less than or equal to the size of the image."
            f" Got window_size: {window_size} and image size: {preds.shape[2]}x{preds.shape[3]}."
        )
    hp_filter = hp_filter[None, None, :, :]
    per_channel = [
        _scc_per_channel(preds[:, c][:, None], target[:, c][:, None], hp_filter, window_size)
        for c in range(preds.shape[1])
    ]
    scc = jnp.concatenate(per_channel, axis=1)
    if reduction in (None, "none"):
        return scc.mean(axis=(1, 2, 3))
    if reduction == "mean":
        return scc.mean()
    raise ValueError(f"Expected reduction to be one of 'mean', 'none', None but got {reduction}")
