"""Pan-sharpening quality metrics: D_lambda, D_s, QNR.

Reference: functional/image/{d_lambda,d_s,qnr}.py — built on per-band UQI.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.misc import universal_image_quality_index
from torchmetrics_tpu.functional.image.utils import _uniform_filter
from torchmetrics_tpu.parallel.sync import reduce


def spectral_distortion_index(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: str = "elementwise_mean",
) -> Array:
    """D_lambda: inter-band UQI difference between fused and MS image (reference d_lambda.py).

    Example:
        >>> from torchmetrics_tpu.functional import spectral_distortion_index
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = spectral_distortion_index(preds, target)
        >>> round(float(result), 4)
        0.0
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    if preds.ndim != 4:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    if preds.shape[:2] != target.shape[:2]:
        raise ValueError(
            "Expected `preds` and `target` to have same batch and channel sizes."
            f"Got preds: {preds.shape} and target: {target.shape}."
        )
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    length = preds.shape[1]
    pairs = [(k, r) for k in range(length) for r in range(k + 1, length)]
    if pairs:
        # batch all band pairs into ONE UQI call each for target and preds
        # (reference d_lambda.py:80-97 batches per band; this is O(1) conv dispatches)
        b = preds.shape[0]
        t1 = jnp.concatenate([target[:, k : k + 1] for k, _ in pairs], axis=0)
        t2 = jnp.concatenate([target[:, r : r + 1] for _, r in pairs], axis=0)
        p1 = jnp.concatenate([preds[:, k : k + 1] for k, _ in pairs], axis=0)
        p2 = jnp.concatenate([preds[:, r : r + 1] for _, r in pairs], axis=0)
        uqi_t = universal_image_quality_index(t1, t2, reduction="none").reshape(len(pairs), -1).mean(-1)
        uqi_p = universal_image_quality_index(p1, p2, reduction="none").reshape(len(pairs), -1).mean(-1)
        rows = jnp.asarray([k for k, _ in pairs])
        cols = jnp.asarray([r for _, r in pairs])
        m1 = jnp.zeros((length, length)).at[rows, cols].set(uqi_t)
        m2 = jnp.zeros((length, length)).at[rows, cols].set(uqi_p)
        m1 = m1 + m1.T
        m2 = m2 + m2.T
    else:
        m1 = jnp.zeros((length, length))
        m2 = jnp.zeros((length, length))
    diff = jnp.abs(m1 - m2) ** p
    if length == 1:
        output = diff ** (1.0 / p)
    else:
        output = (1.0 / (length * (length - 1)) * diff.sum()) ** (1.0 / p)
    return reduce(output, reduction)


def _degrade_pan(pan: Array, ms_shape: Tuple[int, int], window_size: int) -> Array:
    """Low-pass + bilinear downsample of the pan image (reference d_s.py:190-192)."""
    pan_degraded = _uniform_filter(pan, window_size=window_size)
    return jax.image.resize(
        pan_degraded, pan_degraded.shape[:2] + ms_shape, method="bilinear"
    )


def spatial_distortion_index(
    preds: Array,
    ms: Array,
    pan: Array,
    pan_lr: Optional[Array] = None,
    norm_order: int = 1,
    window_size: int = 7,
    reduction: str = "elementwise_mean",
) -> Array:
    """D_s: per-band UQI difference against the pan image (reference d_s.py).

    Example:
        >>> from torchmetrics_tpu.functional import spatial_distortion_index
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(1 * 3 * 32 * 32).reshape(1, 3, 32, 32) % 255) / 255.0
        >>> ms = preds[:, :, ::4, ::4] * 0.9
        >>> pan = preds * 0.95
        >>> result = spatial_distortion_index(preds, ms, pan)
        >>> round(float(result), 4)
        nan
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    ms = jnp.asarray(ms, dtype=jnp.float32)
    pan = jnp.asarray(pan, dtype=jnp.float32)
    if preds.ndim != 4 or ms.ndim != 4 or pan.ndim != 4:
        raise ValueError(f"Expected `preds`, `ms`, `pan` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if preds.shape[:2] != ms.shape[:2] or preds.shape[:2] != pan.shape[:2]:
        raise ValueError("Expected `preds`, `ms`, `pan` to have the same batch and channel sizes.")
    if preds.shape[-2:] != pan.shape[-2:]:
        raise ValueError("Expected `preds` and `pan` to have the same spatial dimension.")
    if not isinstance(norm_order, int) or norm_order <= 0:
        raise ValueError(f"Expected `norm_order` to be a positive integer. Got norm_order: {norm_order}.")
    if not isinstance(window_size, int) or window_size <= 0:
        raise ValueError(f"Expected `window_size` to be a positive integer. Got window_size: {window_size}.")
    ms_h, ms_w = ms.shape[-2:]
    if window_size >= ms_h or window_size >= ms_w:
        raise ValueError(f"Expected `window_size` to be smaller than dimension of `ms`. Got window_size: {window_size}.")

    pan_degraded = pan_lr if pan_lr is not None else _degrade_pan(pan, (ms_h, ms_w), window_size)

    length = preds.shape[1]
    m1 = jnp.stack(
        [universal_image_quality_index(ms[:, i : i + 1], pan_degraded[:, i : i + 1]) for i in range(length)]
    )
    m2 = jnp.stack([universal_image_quality_index(preds[:, i : i + 1], pan[:, i : i + 1]) for i in range(length)])
    diff = jnp.abs(m1 - m2) ** norm_order
    return reduce(diff, reduction) ** (1 / norm_order)


def quality_with_no_reference(
    preds: Array,
    ms: Array,
    pan: Array,
    pan_lr: Optional[Array] = None,
    alpha: float = 1,
    beta: float = 1,
    norm_order: int = 1,
    window_size: int = 7,
    reduction: str = "elementwise_mean",
) -> Array:
    """QNR = (1−D_λ)^α · (1−D_s)^β (reference qnr.py).

    Example:
        >>> from torchmetrics_tpu.functional import quality_with_no_reference
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(1 * 3 * 32 * 32).reshape(1, 3, 32, 32) % 255) / 255.0
        >>> ms = preds[:, :, ::4, ::4] * 0.9
        >>> pan = preds * 0.95
        >>> result = quality_with_no_reference(preds, ms, pan)
        >>> round(float(result), 4)
        nan
    """
    if not isinstance(alpha, (int, float)) or alpha < 0:
        raise ValueError(f"Expected `alpha` to be a non-negative real number. Got alpha: {alpha}.")
    if not isinstance(beta, (int, float)) or beta < 0:
        raise ValueError(f"Expected `beta` to be a non-negative real number. Got beta: {beta}.")
    d_lambda = spectral_distortion_index(preds, ms, p=1, reduction=reduction)
    d_s = spatial_distortion_index(preds, ms, pan, pan_lr, norm_order, window_size, reduction)
    return (1 - d_lambda) ** alpha * (1 - d_s) ** beta
