"""SSIM and MS-SSIM (reference functional/image/ssim.py).

Gaussian (or uniform) windowed statistics computed over a 5×-batched stack
(preds, target, preds², target², preds·target). The separable window runs
through `utils._separable_window_2d`, which dispatches between banded matmuls
(GEMM — MXU-tiled on TPU, BLAS on CPU) for typical image sizes and 1-D grouped
convs for very large ones (reference ssim.py:135-140 uses one dense grouped
torch conv).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.utils import (
    _avg_pool2d,
    _gaussian,
    _reflect_pad_2d,
    _reflect_pad_3d,
    _separable_window_2d,
    _separable_window_3d,
)
from torchmetrics_tpu.utils.checks import _check_same_shape


def _ssim_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim not in (4, 5):
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape. Got preds: {preds.shape}."
        )
    return preds, target


def _ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Union[float, Tuple[float, float], None] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """Per-image SSIM (and optionally CS / full map) — reference ssim.py:50-200.

    Handles both 2-D (NCHW) and 3-D (NCDHW) inputs, like the reference.
    """
    is_3d = preds.ndim == 5
    ndims = 3 if is_3d else 2
    if not isinstance(kernel_size, Sequence):
        kernel_size = ndims * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = ndims * [sigma]
    if len(kernel_size) != ndims or len(sigma) != ndims:
        raise ValueError(
            f"`kernel_size` has dimension {ndims} for {'3d' if is_3d else '2d'} images"
            f" but got kernel_size: {kernel_size} and sigma: {sigma}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    if data_range is None:
        data_range = jnp.maximum(preds.max(), target.max()) - jnp.minimum(preds.min(), target.min())
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = data_range[1] - data_range[0]

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2

    # Both gaussian and uniform windows are separable: run 1-D passes per axis
    # instead of one dense k^2 (k^3) kernel — ~k/2x fewer MACs, same math.
    #
    # Reference quirk (ssim.py:125-143): the GAUSSIAN window's size is derived
    # from sigma — int(3.5*s + 0.5)*2 + 1 per axis — and `kernel_size` only
    # sizes the UNIFORM window; padding/cropping always use the sigma-derived
    # size in both modes.
    gauss_size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]
    if gaussian_kernel:
        k1d = [_gaussian(k, s, preds.dtype) for k, s in zip(gauss_size, sigma)]
    else:
        k1d = [jnp.full((k,), 1.0 / k, dtype=preds.dtype) for k in kernel_size]
    if is_3d:
        pad_d = (gauss_size[0] - 1) // 2
        pad_h = (gauss_size[1] - 1) // 2
        pad_w = (gauss_size[2] - 1) // 2
        preds_p = _reflect_pad_3d(preds, pad_d, pad_h, pad_w)
        target_p = _reflect_pad_3d(target, pad_d, pad_h, pad_w)
        input_list = jnp.concatenate(
            [preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p]
        )
        outputs = _separable_window_3d(input_list, k1d[0], k1d[1], k1d[2])
    else:
        pad_h = (gauss_size[0] - 1) // 2
        pad_w = (gauss_size[1] - 1) // 2
        preds_p = _reflect_pad_2d(preds, pad_h, pad_w)
        target_p = _reflect_pad_2d(target, pad_h, pad_w)

        input_list = jnp.concatenate(
            [preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p]
        )  # (5B, C, H+2p, W+2p)
        outputs = _separable_window_2d(input_list, k1d[0], k1d[1])
    b = preds.shape[0]
    mu_pred = outputs[:b]
    mu_target = outputs[b : 2 * b]
    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = outputs[2 * b : 3 * b] - mu_pred_sq
    sigma_target_sq = outputs[3 * b : 4 * b] - mu_target_sq
    sigma_pred_target = outputs[4 * b :] - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_idx_full_image = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)

    # crop to the valid (unpadded) region
    def _crop(x: Array) -> Array:
        if is_3d:
            return x[..., pad_d:-pad_d, pad_h:-pad_h, pad_w:-pad_w] if pad_d and pad_h and pad_w else x
        return x[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else x

    ssim_idx = _crop(ssim_idx_full_image)
    per_image = ssim_idx.reshape(ssim_idx.shape[0], -1).mean(-1)
    if return_contrast_sensitivity:
        cs = _crop(upper / lower)
        return per_image, cs.reshape(cs.shape[0], -1).mean(-1)
    if return_full_image:
        return per_image, ssim_idx_full_image
    return per_image


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Union[float, Tuple[float, float], None] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """Compute SSIM (reference ssim.py public entry).

    Example:
        >>> from torchmetrics_tpu.functional import structural_similarity_index_measure
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = structural_similarity_index_measure(preds, target)
        >>> round(float(result), 4)
        0.922
    """
    preds, target = _ssim_check_inputs(preds, target)
    out = _ssim_update(
        preds,
        target,
        gaussian_kernel,
        sigma,
        kernel_size,
        data_range,
        k1,
        k2,
        return_full_image,
        return_contrast_sensitivity,
    )
    if isinstance(out, tuple):
        similarity, extra = out
    else:
        similarity, extra = out, None

    if reduction == "elementwise_mean":
        similarity = similarity.mean()
    elif reduction == "sum":
        similarity = similarity.sum()
    if extra is not None:
        return similarity, extra
    return similarity


_MS_SSIM_BETAS = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333)


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Union[float, Tuple[float, float], None] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = _MS_SSIM_BETAS,
    normalize: Optional[str] = "relu",
) -> Array:
    """MS-SSIM over len(betas) scales (reference ssim.py:220+).

    Example:
        >>> from torchmetrics_tpu.functional import multiscale_structural_similarity_index_measure
        >>> import jax.numpy as jnp
        >>> preds = (jnp.arange(2 * 3 * 32 * 32).reshape(2, 3, 32, 32) % 255) / 255.0
        >>> target = preds * 0.75
        >>> result = multiscale_structural_similarity_index_measure(preds, target, betas=(0.5, 0.5))
        >>> round(float(result), 4)
        0.941
    """
    preds, target = _ssim_check_inputs(preds, target)
    if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize not in ("relu", "simple", None):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")

    _ks = kernel_size if isinstance(kernel_size, Sequence) else [kernel_size, kernel_size]
    min_size = (_ks[0] - 1) * 2 ** (len(betas) - 1) + 1
    if preds.shape[-1] < min_size or preds.shape[-2] < min_size:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width should be larger than"
            f" {min_size} but got height: {preds.shape[-2]} and width: {preds.shape[-1]}"
        )

    sim_list: List[Array] = []
    cs_list: List[Array] = []
    p, t = preds, target
    for _ in range(len(betas)):
        sim, cs = _ssim_update(
            p, t, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, return_contrast_sensitivity=True
        )
        sim_list.append(sim)
        cs_list.append(cs)
        p = _avg_pool2d(p, 2)
        t = _avg_pool2d(t, 2)

    mcs_and_ssim = jnp.stack(cs_list[:-1] + [sim_list[-1]], axis=0)  # (S, B)
    if normalize == "relu":
        mcs_and_ssim = jnp.maximum(mcs_and_ssim, 0.0)
    elif normalize == "simple":
        mcs_and_ssim = (mcs_and_ssim + 1) / 2
    betas_arr = jnp.asarray(betas)[:, None]
    ms_ssim = jnp.prod(mcs_and_ssim**betas_arr, axis=0)

    if reduction == "elementwise_mean":
        return ms_ssim.mean()
    if reduction == "sum":
        return ms_ssim.sum()
    return ms_ssim
