"""Label-comparison clustering metrics (reference functional/clustering/
{mutual_info,normalized_mutual_info,adjusted_mutual_info,rand,adjusted_rand,
fowlkes_mallows,homogeneity_completeness_v_measure}*.py).

All reduce to the contingency matrix; the EMI triple loop of the reference
(sklearn's _expected_mutual_info_fast port) is replaced by one masked 3-D
grid evaluation.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.clustering.utils import (
    _validate_average_method_arg,
    calculate_contingency_matrix,
    calculate_entropy,
    calculate_generalized_mean,
    calculate_pair_cluster_confusion_matrix,
    check_cluster_labels,
)


def _mutual_info_score_compute(contingency: Array) -> Array:
    contingency = contingency.astype(jnp.float32)
    n = contingency.sum()
    u = contingency.sum(axis=1)
    v = contingency.sum(axis=0)
    if u.size == 1 or v.size == 1:
        return jnp.asarray(0.0)
    nz = contingency > 0
    log_outer = jnp.log(jnp.clip(u[:, None], 1e-30)) + jnp.log(jnp.clip(v[None, :], 1e-30))
    terms = jnp.where(
        nz,
        contingency / n * (jnp.log(n) + jnp.log(jnp.clip(contingency, 1e-30)) - log_outer),
        0.0,
    )
    return terms.sum()


def mutual_info_score(preds: Array, target: Array) -> Array:
    """MI between two label assignments.

    Example:
        >>> from torchmetrics_tpu.functional import mutual_info_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = mutual_info_score(preds, target)
        >>> round(float(result), 4)
        0.5004
    """
    check_cluster_labels(jnp.asarray(preds), jnp.asarray(target))
    return _mutual_info_score_compute(calculate_contingency_matrix(preds, target))


def normalized_mutual_info_score(
    preds: Array, target: Array, average_method: str = "arithmetic"
) -> Array:
    """NMI: MI / generalized-mean of entropies.

    Example:
        >>> from torchmetrics_tpu.functional import normalized_mutual_info_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = normalized_mutual_info_score(preds, target)
        >>> round(float(result), 4)
        0.4744
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    check_cluster_labels(preds, target)
    _validate_average_method_arg(average_method)
    mutual_info = mutual_info_score(preds, target)
    if bool(jnp.isclose(mutual_info, 0.0, atol=jnp.finfo(jnp.float32).eps)):
        return mutual_info
    normalizer = calculate_generalized_mean(
        jnp.stack([calculate_entropy(preds), calculate_entropy(target)]), average_method
    )
    return mutual_info / normalizer


def expected_mutual_info_score(contingency: Array, n_samples: int) -> Array:
    """EMI under the permutation model, vectorized over the (i, j, nij) grid.

    Reference adjusted_mutual_info_score.py:expected_mutual_info_score runs a
    Python triple loop; here the hypergeometric terms are evaluated on the full
    (rows, cols, n_max+1) grid with a validity mask and summed in one shot.
    Runs in host float64 (scipy gammaln): the exp-of-log-gamma differences
    cancel catastrophically in float32, and EMI is a one-off scalar correction
    at compute time, not a hot-loop kernel.
    """
    import numpy as np
    from scipy.special import gammaln as np_gammaln

    cont = np.asarray(contingency, dtype=np.float64)
    a = cont.sum(axis=1)  # (R,)
    b = cont.sum(axis=0)  # (C,)
    if a.size == 1 or b.size == 1:
        return jnp.asarray(0.0)
    n = float(n_samples)
    n_max = int(max(a.max(), b.max()))
    nijs = np.arange(0, n_max + 1, dtype=np.float64)
    nijs[0] = 1.0
    term1 = nijs / n

    log_b = np.log(b)
    log_nnij = np.log(n) + np.log(nijs)
    gln_a = np_gammaln(a + 1)
    gln_b = np_gammaln(b + 1)
    gln_na = np_gammaln(n - a + 1)
    gln_nb = np_gammaln(n - b + 1)
    gln_nnij = np_gammaln(nijs + 1) + np_gammaln(n + 1)

    # mask on the raw index, not nijs (whose slot 0 is rewritten to 1.0 and
    # would otherwise double-count the nij=1 term)
    idx = np.arange(0, n_max + 1, dtype=np.float64)[None, :]
    nij = nijs[None, :]
    bv = b[:, None]

    # evaluate one row of the (i, j, nij) grid at a time: O(C * n_max) memory
    # instead of 10 dense (R, C, n_max) temporaries
    emi = 0.0
    for i in range(a.size):
        av = a[i]
        start = np.maximum(1.0, av - n + bv)
        end = np.minimum(av, bv) + 1
        valid = (idx >= start) & (idx < end)  # (C, n_max+1)
        gln = (
            gln_a[i]
            + gln_b[:, None]
            + gln_na[i]
            + gln_nb[:, None]
            - gln_nnij[None, :]
            - np_gammaln(np.clip(av - nij + 1, 1e-6, None))
            - np_gammaln(np.clip(bv - nij + 1, 1e-6, None))
            - np_gammaln(np.clip(n - av - bv + nij + 1, 1e-6, None))
        )
        term2 = log_nnij[None, :] - np.log(a[i]) - log_b[:, None]
        emi += np.sum(np.where(valid, term1[None, :] * term2 * np.exp(gln), 0.0))
    return jnp.asarray(emi, dtype=jnp.float32)


def adjusted_mutual_info_score(
    preds: Array, target: Array, average_method: str = "arithmetic"
) -> Array:
    """AMI: (MI - E[MI]) / (normalizer - E[MI]).

    Example:
        >>> from torchmetrics_tpu.functional import adjusted_mutual_info_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = adjusted_mutual_info_score(preds, target)
        >>> round(float(result), 4)
        -0.25
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _validate_average_method_arg(average_method)
    check_cluster_labels(preds, target)
    contingency = calculate_contingency_matrix(preds, target)
    mutual_info = _mutual_info_score_compute(contingency)
    emi = expected_mutual_info_score(contingency, int(target.size))
    normalizer = calculate_generalized_mean(
        jnp.stack([calculate_entropy(preds), calculate_entropy(target)]), average_method
    )
    denominator = normalizer - emi
    eps = jnp.finfo(jnp.float32).eps
    denominator = jnp.where(
        denominator < 0, jnp.minimum(denominator, -eps), jnp.maximum(denominator, eps)
    )
    return (mutual_info - emi) / denominator


def rand_score(preds: Array, target: Array) -> Array:
    """Rand index from the 2x2 pair confusion matrix.

    Example:
        >>> from torchmetrics_tpu.functional import rand_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = rand_score(preds, target)
        >>> round(float(result), 4)
        0.6
    """
    check_cluster_labels(jnp.asarray(preds), jnp.asarray(target))
    contingency = calculate_contingency_matrix(preds, target)
    pair_matrix = calculate_pair_cluster_confusion_matrix(contingency=contingency)
    numerator = jnp.diagonal(pair_matrix).sum()
    denominator = pair_matrix.sum()
    if bool(numerator == denominator) or bool(denominator == 0):
        return jnp.asarray(1.0)
    return (numerator / denominator).astype(jnp.float32)


def adjusted_rand_score(preds: Array, target: Array) -> Array:
    """ARI from the 2x2 pair confusion matrix.

    Example:
        >>> from torchmetrics_tpu.functional import adjusted_rand_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = adjusted_rand_score(preds, target)
        >>> round(float(result), 4)
        -0.25
    """
    check_cluster_labels(jnp.asarray(preds), jnp.asarray(target))
    contingency = calculate_contingency_matrix(preds, target)
    pair_matrix = calculate_pair_cluster_confusion_matrix(contingency=contingency)
    (tn, fp), (fn, tp) = pair_matrix
    if bool(fn == 0) and bool(fp == 0):
        return jnp.asarray(1.0)
    return (2.0 * (tp * tn - fn * fp) / ((tp + fn) * (fn + tn) + (tp + fp) * (fp + tn))).astype(jnp.float32)


def fowlkes_mallows_index(preds: Array, target: Array) -> Array:
    """FMI: geometric mean of pairwise precision and recall.

    Example:
        >>> from torchmetrics_tpu.functional import fowlkes_mallows_index
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = fowlkes_mallows_index(preds, target)
        >>> round(float(result), 4)
        0.0
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    check_cluster_labels(preds, target)
    contingency = calculate_contingency_matrix(preds, target).astype(jnp.float32)
    n = preds.shape[0]
    tk = jnp.sum(contingency**2) - n
    if bool(jnp.isclose(tk, 0.0)):
        return jnp.asarray(0.0)
    pk = jnp.sum(contingency.sum(axis=0) ** 2) - n
    qk = jnp.sum(contingency.sum(axis=1) ** 2) - n
    return jnp.sqrt(tk / pk) * jnp.sqrt(tk / qk)


def _homogeneity_score_compute(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array]:
    check_cluster_labels(preds, target)
    if target.size == 0:
        zero = jnp.asarray(0.0)
        return zero, zero, zero, zero
    entropy_target = calculate_entropy(target)
    entropy_preds = calculate_entropy(preds)
    mutual_info = mutual_info_score(preds, target)
    homogeneity = mutual_info / entropy_target if bool(entropy_target) else jnp.ones_like(entropy_target)
    return homogeneity, mutual_info, entropy_preds, entropy_target


def homogeneity_score(preds: Array, target: Array) -> Array:
    """Each predicted cluster contains only members of a single class.

    Example:
        >>> from torchmetrics_tpu.functional import homogeneity_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = homogeneity_score(preds, target)
        >>> round(float(result), 4)
        0.4744
    """
    return _homogeneity_score_compute(jnp.asarray(preds), jnp.asarray(target))[0]


def completeness_score(preds: Array, target: Array) -> Array:
    """All members of a class are assigned to the same cluster.

    Example:
        >>> from torchmetrics_tpu.functional import completeness_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = completeness_score(preds, target)
        >>> round(float(result), 4)
        0.4744
    """
    homogeneity, mutual_info, entropy_preds, _ = _homogeneity_score_compute(jnp.asarray(preds), jnp.asarray(target))
    return mutual_info / entropy_preds if bool(entropy_preds) else jnp.ones_like(entropy_preds)


def v_measure_score(preds: Array, target: Array, beta: float = 1.0) -> Array:
    """Weighted harmonic mean of homogeneity and completeness.

    Example:
        >>> from torchmetrics_tpu.functional import v_measure_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> result = v_measure_score(preds, target)
        >>> round(float(result), 4)
        0.4744
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    homogeneity, mutual_info, entropy_preds, _ = _homogeneity_score_compute(preds, target)
    completeness = mutual_info / entropy_preds if bool(entropy_preds) else jnp.ones_like(entropy_preds)
    if bool(homogeneity + completeness == 0.0):
        return jnp.ones_like(homogeneity)
    return (1 + beta) * homogeneity * completeness / (beta * homogeneity + completeness)
