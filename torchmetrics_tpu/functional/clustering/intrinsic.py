"""Embedding-based clustering metrics (reference functional/clustering/
{calinski_harabasz,davies_bouldin,dunn_index}.py).

The reference loops over clusters in Python; here every per-cluster statistic
(centroid, dispersion, intra-distance) is a ``segment_sum``/``segment_max``
over the label vector — one fused reduction regardless of cluster count.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.clustering.utils import (
    _validate_intrinsic_cluster_data,
    _validate_intrinsic_labels_to_samples,
)


def _relabel(data: Array, labels: Array):
    data = jnp.asarray(data)
    labels = jnp.asarray(labels)
    _validate_intrinsic_cluster_data(data, labels)
    unique_labels, labels = jnp.unique(labels, return_inverse=True)
    num_labels = int(unique_labels.shape[0])
    _validate_intrinsic_labels_to_samples(num_labels, data.shape[0])
    return data, labels.reshape(-1), num_labels


def _centroids_counts(data: Array, labels: Array, num_labels: int):
    counts = jax.ops.segment_sum(jnp.ones(data.shape[0]), labels, num_segments=num_labels)
    sums = jax.ops.segment_sum(data, labels, num_segments=num_labels)
    return sums / counts[:, None], counts


def calinski_harabasz_score(data: Array, labels: Array) -> Array:
    """Variance-ratio criterion: between/within cluster dispersion.

    Example:
        >>> from torchmetrics_tpu.functional import calinski_harabasz_score
        >>> import jax.numpy as jnp
        >>> data = jnp.asarray([[0.0, 0.1], [0.1, 0.0], [4.0, 4.1], [4.1, 4.0], [8.0, 8.1], [8.1, 8.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> result = calinski_harabasz_score(data, labels)
        >>> round(float(result), 4)
        6399.9868
    """
    data, labels, num_labels = _relabel(data, labels)
    num_samples = data.shape[0]
    mean = data.mean(axis=0)
    centroids, counts = _centroids_counts(data, labels, num_labels)
    between = jnp.sum(counts * jnp.sum((centroids - mean) ** 2, axis=1))
    within = jnp.sum((data - centroids[labels]) ** 2)
    if bool(within == 0):
        return jnp.asarray(1.0)
    return between * (num_samples - num_labels) / (within * (num_labels - 1.0))


def davies_bouldin_score(data: Array, labels: Array) -> Array:
    """Mean worst-case ratio of intra-cluster spread to centroid separation.

    Example:
        >>> from torchmetrics_tpu.functional import davies_bouldin_score
        >>> import jax.numpy as jnp
        >>> data = jnp.asarray([[0.0, 0.1], [0.1, 0.0], [4.0, 4.1], [4.1, 4.0], [8.0, 8.1], [8.1, 8.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> result = davies_bouldin_score(data, labels)
        >>> round(float(result), 4)
        0.025
    """
    data, labels, num_labels = _relabel(data, labels)
    centroids, counts = _centroids_counts(data, labels, num_labels)
    dists = jnp.sqrt(jnp.sum((data - centroids[labels]) ** 2, axis=1))
    intra = jax.ops.segment_sum(dists, labels, num_segments=num_labels) / counts
    diff = centroids[:, None, :] - centroids[None, :, :]
    centroid_distances = jnp.sqrt(jnp.sum(diff**2, axis=-1))
    if bool(jnp.allclose(intra, 0.0)) or bool(jnp.allclose(centroid_distances, 0.0)):
        return jnp.asarray(0.0)
    centroid_distances = jnp.where(centroid_distances == 0, jnp.inf, centroid_distances)
    combined = intra[None, :] + intra[:, None]
    scores = jnp.max(combined / centroid_distances, axis=1)
    return scores.mean()


def dunn_index(data: Array, labels: Array, p: float = 2) -> Array:
    """Min inter-centroid distance over max intra-cluster radius.

    Example:
        >>> from torchmetrics_tpu.functional import dunn_index
        >>> import jax.numpy as jnp
        >>> data = jnp.asarray([[0.0, 0.1], [0.1, 0.0], [4.0, 4.1], [4.1, 4.0], [8.0, 8.1], [8.1, 8.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> result = dunn_index(data, labels)
        >>> round(float(result), 4)
        79.9997
    """
    data, labels, num_labels = _relabel(data, labels)
    centroids, _ = _centroids_counts(data, labels, num_labels)
    diff = centroids[:, None, :] - centroids[None, :, :]
    inter = jnp.linalg.norm(diff, ord=p, axis=-1)
    inter = jnp.where(jnp.eye(num_labels, dtype=bool), jnp.inf, inter)
    radii = jnp.linalg.norm(data - centroids[labels], ord=p, axis=-1)
    max_intra = jax.ops.segment_max(radii, labels, num_segments=num_labels)
    return inter.min() / max_intra.max()
