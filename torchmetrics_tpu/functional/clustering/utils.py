"""Clustering helpers (reference functional/clustering/utils.py).

The contingency matrix — the one data structure every extrinsic clustering
metric reduces to — is built dense with a relabel + bincount (one fused gather
on device) instead of the reference's sparse COO tensor.
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def is_nonnegative(x: Array, atol: float = 1e-5) -> bool:
    return bool(jnp.all(x >= -atol))


def _validate_average_method_arg(average_method: str) -> None:
    if average_method not in ("min", "geometric", "arithmetic", "max"):
        raise ValueError(
            "Expected argument `average_method` to be one of `min`, `geometric`, `arithmetic`, `max`,"
            f" but got {average_method}"
        )


def calculate_entropy(x: Array) -> Array:
    """Entropy of a label assignment (reference utils.py:47-76)."""
    x = jnp.asarray(x).reshape(-1)
    if x.size == 0:
        return jnp.asarray(1.0)
    _, inv = jnp.unique(x, return_inverse=True)
    p = jnp.bincount(inv.reshape(-1))
    p = p[p > 0]
    if p.size == 1:
        return jnp.asarray(0.0)
    n = p.sum()
    return -jnp.sum((p / n) * (jnp.log(p) - jnp.log(n)))


def calculate_generalized_mean(x: Array, p: Union[int, str]) -> Array:
    """Power mean (reference utils.py:78-118)."""
    if jnp.iscomplexobj(x) or not is_nonnegative(x):
        raise ValueError("`x` must contain positive real numbers")
    if isinstance(p, str):
        if p == "min":
            return x.min()
        if p == "geometric":
            return jnp.exp(jnp.mean(jnp.log(x)))
        if p == "arithmetic":
            return x.mean()
        if p == "max":
            return x.max()
        raise ValueError("'method' must be 'min', 'geometric', 'arirthmetic', or 'max'")
    return jnp.mean(jnp.power(x, p)) ** (1.0 / p)


def calculate_contingency_matrix(preds: Array, target: Array, eps: Optional[float] = None) -> Array:
    """Dense contingency matrix of shape (n_classes_target, n_classes_preds)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim != 1 or target.ndim != 1:
        raise ValueError(f"Expected 1d `preds` and `target` but got {preds.ndim} and {target.ndim}.")
    _, preds_idx = jnp.unique(preds, return_inverse=True)
    _, target_idx = jnp.unique(target, return_inverse=True)
    n_p = int(preds_idx.max()) + 1
    n_t = int(target_idx.max()) + 1
    contingency = jnp.bincount(
        (target_idx * n_p + preds_idx).reshape(-1), length=n_t * n_p
    ).reshape(n_t, n_p)
    if eps is not None:
        contingency = contingency.astype(jnp.float32) + eps
    return contingency


def _is_real_discrete_label(x: Array) -> bool:
    if x.ndim != 1:
        raise ValueError(f"Expected arguments to be 1-d tensors but got {x.ndim}-d tensors.")
    return not (jnp.issubdtype(x.dtype, jnp.floating) or jnp.issubdtype(x.dtype, jnp.complexfloating))


def check_cluster_labels(preds: Array, target: Array) -> None:
    """Validate 1-d discrete label tensors (reference utils.py:183-194)."""
    _check_same_shape(preds, target)
    if not (_is_real_discrete_label(preds) and _is_real_discrete_label(target)):
        raise ValueError(f"Expected real, discrete values but received {preds.dtype} and {target.dtype}.")


def _validate_intrinsic_cluster_data(data: Array, labels: Array) -> None:
    if data.ndim != 2:
        raise ValueError(f"Expected 2D data, got {data.ndim}D data instead")
    if not jnp.issubdtype(data.dtype, jnp.floating):
        raise ValueError(f"Expected floating point data, got {data.dtype} data instead")
    if labels.ndim != 1:
        raise ValueError(f"Expected 1D labels, got {labels.ndim}D labels instead")


def _validate_intrinsic_labels_to_samples(num_labels: int, num_samples: int) -> None:
    if not 1 < num_labels < num_samples:
        raise ValueError(
            "Number of detected clusters must be greater than one and less than the number of samples."
            f"Got {num_labels} clusters and {num_samples} samples."
        )


def calculate_pair_cluster_confusion_matrix(
    preds: Optional[Array] = None,
    target: Optional[Array] = None,
    contingency: Optional[Array] = None,
) -> Array:
    """2x2 pair confusion matrix over sample pairs (reference utils.py:215-283)."""
    if preds is None and target is None and contingency is None:
        raise ValueError("Must provide either `preds` and `target` or `contingency`.")
    if preds is not None and target is not None and contingency is not None:
        raise ValueError("Must provide either `preds` and `target` or `contingency`, not both.")
    if contingency is None:
        contingency = calculate_contingency_matrix(preds, target)
    contingency = contingency.astype(jnp.float64 if contingency.dtype == jnp.float64 else jnp.float32)
    n_samples = contingency.sum()
    n_c = contingency.sum(axis=1)
    n_k = contingency.sum(axis=0)
    sum_squares = (contingency**2).sum()
    pair_matrix = jnp.zeros((2, 2), dtype=contingency.dtype)
    pair_matrix = pair_matrix.at[1, 1].set(sum_squares - n_samples)
    pair_matrix = pair_matrix.at[0, 1].set((contingency @ n_k).sum() - sum_squares)
    pair_matrix = pair_matrix.at[1, 0].set((contingency.T @ n_c).sum() - sum_squares)
    pair_matrix = pair_matrix.at[0, 0].set(n_samples**2 - pair_matrix[0, 1] - pair_matrix[1, 0] - sum_squares)
    return pair_matrix
