"""Segmentation groundwork utilities (reference functional/segmentation/utils.py).

Binary morphology and distance machinery for boundary metrics. TPU notes:

- ``binary_erosion`` unrolls the (static, <=27-element) structuring element into
  shifted-slice ANDs — XLA fuses these into one elementwise kernel, no im2col
  unfold matrix needed.
- ``distance_transform``'s default engine is the same all-pairs formulation as
  the reference's pytorch engine (O(N^2) worst-case memory, fine for the mask
  sizes boundary metrics see); the scipy engine is the memory-lean host
  fallback.
- ``spacing`` tables: the 2-D contour-length table is formula-driven from the
  pixel spacing; the 3-D surface-area table scales the marching-cubes normal
  lookup (``_surface_normals.npz``, public deepmind/surface-distance data) by
  the per-face voxel areas.
"""
from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def check_if_binarized(x: Array) -> None:
    """Raise unless every element is 0 or 1."""
    if not bool(jnp.all(x.astype(bool) == x)):
        raise ValueError("Input x should be binarized")


def generate_binary_structure(rank: int, connectivity: int) -> Array:
    """scipy.ndimage-compatible structuring element (reference utils.py:64-105)."""
    if connectivity < 1:
        connectivity = 1
    if rank < 1:
        return jnp.asarray([1], dtype=jnp.uint8).astype(bool)
    grids = jnp.meshgrid(*[jnp.arange(3) for _ in range(rank)], indexing="ij")
    output = jnp.abs(jnp.stack(grids, axis=0) - 1)
    return jnp.sum(output, axis=0) <= connectivity


def binary_erosion(
    image: Array,
    structure: Optional[Array] = None,
    origin: Optional[Tuple[int, ...]] = None,
    border_value: int = 0,
) -> Array:
    """Binary erosion over ``(B, C, *spatial)`` images (reference utils.py:107-174).

    A pixel survives iff every neighbour selected by the structuring element is
    set. The structure is static, so the erosion unrolls to an AND over shifted
    views — one fused elementwise XLA op chain.
    """
    image = jnp.asarray(image)
    if image.ndim not in [4, 5]:
        raise ValueError(f"Expected argument `image` to be of rank 4 or 5 but found rank {image.ndim}")
    check_if_binarized(image)

    rank = image.ndim - 2
    if structure is None:
        structure = generate_binary_structure(rank, 1)
    structure = jnp.asarray(structure)
    check_if_binarized(structure)
    if origin is None:
        origin = structure.ndim * (1,)

    pad_width = [(0, 0), (0, 0)] + [
        (origin[i], structure.shape[i] - origin[i] - 1) for i in range(len(origin))
    ]
    padded = jnp.pad(image.astype(bool), pad_width, constant_values=bool(border_value))

    struct_np = np.asarray(structure)
    out = jnp.ones(image.shape, dtype=bool)
    spatial = image.shape[2:]
    for offset in np.argwhere(struct_np):
        sl = (slice(None), slice(None)) + tuple(slice(int(o), int(o) + s) for o, s in zip(offset, spatial))
        out = out & padded[sl]
    return out.astype(jnp.uint8)


def distance_transform(
    x: Array,
    sampling: Optional[Union[Array, List[float]]] = None,
    metric: str = "euclidean",
    engine: str = "pytorch",
) -> Array:
    """Distance of each foreground pixel to the nearest background pixel.

    Reference utils.py:177-277. ``engine='pytorch'`` maps to the on-device
    all-pairs formulation; ``engine='scipy'`` runs scipy.ndimage on host.
    """
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be of rank 2 but got rank `{x.ndim}`.")
    if sampling is not None and not isinstance(sampling, list):
        raise ValueError(
            f"Expected argument `sampling` to either be `None` or of type `list` but got `{type(sampling)}`."
        )
    if metric not in ["euclidean", "chessboard", "taxicab"]:
        raise ValueError(
            f"Expected argument `metric` to be one of `['euclidean', 'chessboard', 'taxicab']` but got `{metric}`."
        )
    if engine not in ["pytorch", "scipy"]:
        raise ValueError(f"Expected argument `engine` to be one of `['pytorch', 'scipy']` but got `{engine}`.")

    if sampling is None:
        sampling = [1, 1]
    if len(sampling) != 2:
        raise ValueError("Sampling must have length 2")

    if engine == "scipy":
        from scipy import ndimage

        x_np = np.asarray(x)
        if metric == "euclidean":
            return jnp.asarray(ndimage.distance_transform_edt(x_np, sampling))
        return jnp.asarray(
            ndimage.distance_transform_cdt(x_np, metric="chessboard" if metric == "chessboard" else "taxicab")
        ).astype(jnp.float32)

    h, w = x.shape
    ii, jj = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij")
    coords = jnp.stack([ii.reshape(-1) * sampling[0], jj.reshape(-1) * sampling[1]], axis=1)  # (N, 2)
    flat = x.reshape(-1)
    bg = flat == 0
    d = coords[:, None, :] - coords[None, :, :]  # (N, N, 2)
    if metric == "euclidean":
        dist = jnp.sqrt(jnp.sum(d**2, axis=-1))
    elif metric == "chessboard":
        dist = jnp.max(jnp.abs(d), axis=-1)
    else:
        dist = jnp.sum(jnp.abs(d), axis=-1)
    dist_to_bg = jnp.min(jnp.where(bg[None, :], dist, jnp.inf), axis=1)
    out = jnp.where(flat != 0, dist_to_bg, 0.0)
    return out.reshape(h, w)


@lru_cache
def table_contour_length(spacing: Tuple[int, int]) -> Tuple[Array, Array]:
    """Neighbour-code -> contour length table for 2-D masks (reference utils.py:408-449).

    Each 2x2 neighbourhood encodes to a 4-bit code via the [[8,4],[2,1]]
    kernel; the table is derived from the pixel spacing (marching-squares
    segment lengths).
    """
    if not isinstance(spacing, tuple) or len(spacing) != 2:
        raise ValueError("The spacing must be a tuple of length 2.")
    first, second = spacing
    diag = 0.5 * math.sqrt(first**2 + second**2)
    table = np.zeros(16, dtype=np.float32)
    for i in [1, 2, 4, 7, 8, 11, 13, 14]:
        table[i] = diag
    for i in [3, 12]:
        table[i] = second
    for i in [5, 10]:
        table[i] = first
    for i in [6, 9]:
        table[i] = 2 * diag
    kernel = jnp.asarray([[8, 4], [2, 1]], dtype=jnp.float32)
    return jnp.asarray(table), kernel


@lru_cache
def _surface_normals() -> np.ndarray:
    """The 256-code marching-cubes surface-normal lookup, shape (256, 4, 3).

    Public lookup data from deepmind/surface-distance (Apache-2.0), the same
    table the reference embeds at functional/segmentation/utils.py:452; stored
    here as a binary fixture (tools/gen_surface_tables.py documents the
    extraction)."""
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_surface_normals.npz")
    return np.load(path)["normals"]


@lru_cache
def table_surface_area(spacing: Tuple[int, int, int]) -> Tuple[Array, Array]:
    """Neighbour-code -> surface area table for 3-D masks (reference utils.py:452-532).

    Each 2x2x2 neighbourhood encodes to an 8-bit code via the
    [[[128,64],[32,16]],[[8,4],[2,1]]] kernel; a code's area is the sum of the
    norms of its marching-cubes surface normals scaled by the per-face voxel
    areas (s1*s2, s0*s2, s0*s1)."""
    if not isinstance(spacing, tuple) or len(spacing) != 3:
        raise ValueError("The spacing must be a tuple of length 3.")
    normals = _surface_normals()  # (256, 4, 3)
    face = np.asarray(
        [spacing[1] * spacing[2], spacing[0] * spacing[2], spacing[0] * spacing[1]], dtype=np.float32
    )
    table = np.linalg.norm(normals * face, axis=-1).sum(-1)
    kernel = jnp.asarray([[[128, 64], [32, 16]], [[8, 4], [2, 1]]], dtype=jnp.float32)
    return jnp.asarray(table), kernel


def get_neighbour_tables(spacing: Union[Tuple[int, int], Tuple[int, int, int]]) -> Tuple[Array, Array]:
    """Dispatch to the contour-length (2-D) or surface-area (3-D) table
    (reference utils.py:387-405)."""
    if isinstance(spacing, tuple) and len(spacing) == 2:
        return table_contour_length(spacing)
    if isinstance(spacing, tuple) and len(spacing) == 3:
        return table_surface_area(spacing)
    raise ValueError("The spacing must be a tuple of length 2 or 3.")


def _neighbour_codes_2d(mask: Array, kernel: Array) -> Array:
    """Valid-mode 2x2 correlation producing the neighbour code per position."""
    m = mask.astype(jnp.float32)
    return (
        m[:-1, :-1] * kernel[0, 0]
        + m[:-1, 1:] * kernel[0, 1]
        + m[1:, :-1] * kernel[1, 0]
        + m[1:, 1:] * kernel[1, 1]
    ).astype(jnp.int32)


def _neighbour_codes_3d(mask: Array, kernel: Array) -> Array:
    """Valid-mode 2x2x2 correlation producing the neighbour code per position."""
    m = mask.astype(jnp.float32)
    out = jnp.zeros(tuple(s - 1 for s in m.shape), dtype=jnp.float32)
    for i in range(2):
        for j in range(2):
            for k in range(2):
                sl = (slice(i, m.shape[0] - 1 + i), slice(j, m.shape[1] - 1 + j), slice(k, m.shape[2] - 1 + k))
                out = out + m[sl] * kernel[i, j, k]
    return out.astype(jnp.int32)


def mask_edges(
    preds: Array,
    target: Array,
    crop: bool = True,
    spacing: Optional[Tuple[int, ...]] = None,
):
    """Edges (and, with spacing, per-position contour/surface areas) of two
    binary masks.

    Reference utils.py:278-333. Without spacing: edge = mask XOR eroded(mask).
    With spacing: neighbour-code table lookup (marching squares in 2-D,
    marching-cubes surface areas in 3-D).
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    if preds.ndim not in [2, 3]:
        raise ValueError(f"Expected argument `preds` to be of rank 2 or 3 but got rank `{preds.ndim}`.")
    check_if_binarized(preds)
    check_if_binarized(target)
    preds = preds.astype(bool)
    target = target.astype(bool)

    if crop:
        if not bool(jnp.any(preds | target)):
            p, t = jnp.zeros_like(preds), jnp.zeros_like(target)
            return p, t, p, t
        pad_width = preds.ndim * [(1, 1)]
        preds = jnp.pad(preds, pad_width)
        target = jnp.pad(target, pad_width)

    if spacing is None:
        be_pred = binary_erosion(preds[None, None]).squeeze((0, 1)).astype(bool) ^ preds
        be_target = binary_erosion(target[None, None]).squeeze((0, 1)).astype(bool) ^ target
        return be_pred, be_target

    if len(spacing) != preds.ndim:
        raise ValueError(f"`spacing` length {len(spacing)} must match the mask rank {preds.ndim}.")
    table, kernel = get_neighbour_tables(spacing)
    codes = _neighbour_codes_3d if len(spacing) == 3 else _neighbour_codes_2d
    code_preds = codes(preds, kernel)
    code_target = codes(target, kernel)
    all_ones = table.shape[0] - 1
    edges_preds = (code_preds != 0) & (code_preds != all_ones)
    edges_target = (code_target != 0) & (code_target != all_ones)
    areas_preds = table[code_preds]
    areas_target = table[code_target]
    return edges_preds, edges_target, areas_preds, areas_target


def surface_distance(
    preds: Array,
    target: Array,
    distance_metric: str = "euclidean",
    spacing: Optional[Union[Array, List[float]]] = None,
) -> Array:
    """Distances from each predicted edge pixel to the nearest target edge pixel.

    Reference utils.py:336-384: distance transform of the complement of the
    target edge mask, gathered at predicted edge positions.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if not (preds.dtype == bool and target.dtype == bool):
        raise ValueError(f"Expected both inputs to be of type bool, but got {preds.dtype} and {target.dtype}.")

    if not bool(jnp.any(target)):
        dis = jnp.inf * jnp.ones(target.shape)
    else:
        if not bool(jnp.any(preds)):
            dis = jnp.inf * jnp.ones(preds.shape)
            return dis[target]
        dis = distance_transform(~target, sampling=spacing, metric=distance_metric)
    return dis[preds]
