from torchmetrics_tpu.functional.segmentation.utils import (  # noqa: F401
    binary_erosion,
    check_if_binarized,
    distance_transform,
    generate_binary_structure,
    get_neighbour_tables,
    mask_edges,
    surface_distance,
    table_contour_length,
    table_surface_area,
)

__all__ = [
    "binary_erosion",
    "check_if_binarized",
    "distance_transform",
    "generate_binary_structure",
    "get_neighbour_tables",
    "mask_edges",
    "surface_distance",
    "table_contour_length",
    "table_surface_area",
]
