"""Box algebra + IoU family (reference functional/detection/{iou,giou,diou,ciou}.py).

The reference delegates to torchvision's C++ ops (functional/detection/iou.py:24-29);
here the box math is plain batched JAX — a handful of fused elementwise ops that XLA
maps straight onto the VPU, no custom kernel needed.

Boxes are ``(x1, y1, x2, y2)`` rows; all pairwise fns take ``(N, 4), (M, 4)`` and
return ``(N, M)``.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

_EPS = 1e-7


def box_convert(boxes: Array, in_fmt: str, out_fmt: str = "xyxy") -> Array:
    """Convert between xyxy / xywh / cxcywh box formats."""
    boxes = jnp.asarray(boxes, dtype=jnp.float32)
    if in_fmt == out_fmt:
        return boxes
    if in_fmt == "xywh":
        x, y, w, h = jnp.split(boxes, 4, axis=-1)
        boxes = jnp.concatenate([x, y, x + w, y + h], axis=-1)
    elif in_fmt == "cxcywh":
        cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
        boxes = jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    elif in_fmt != "xyxy":
        raise ValueError(f"Unknown box format {in_fmt}")
    if out_fmt == "xyxy":
        return boxes
    if out_fmt == "xywh":
        x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
        return jnp.concatenate([x1, y1, x2 - x1, y2 - y1], axis=-1)
    if out_fmt == "cxcywh":
        x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
        return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], axis=-1)
    raise ValueError(f"Unknown box format {out_fmt}")


def box_area(boxes: Array) -> Array:
    boxes = jnp.asarray(boxes, dtype=jnp.float32)
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _inter_union(boxes1: Array, boxes2: Array):
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return inter, union


def box_iou(boxes1: Array, boxes2: Array) -> Array:
    boxes1 = jnp.asarray(boxes1, dtype=jnp.float32).reshape(-1, 4)
    boxes2 = jnp.asarray(boxes2, dtype=jnp.float32).reshape(-1, 4)
    inter, union = _inter_union(boxes1, boxes2)
    return inter / (union + _EPS)


def generalized_box_iou(boxes1: Array, boxes2: Array) -> Array:
    """GIoU: IoU - (hull \\ union) / hull."""
    boxes1 = jnp.asarray(boxes1, dtype=jnp.float32).reshape(-1, 4)
    boxes2 = jnp.asarray(boxes2, dtype=jnp.float32).reshape(-1, 4)
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / (union + _EPS)
    lt = jnp.minimum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.maximum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    hull = wh[..., 0] * wh[..., 1]
    return iou - (hull - union) / (hull + _EPS)


def distance_box_iou(boxes1: Array, boxes2: Array) -> Array:
    """DIoU: IoU - center-distance^2 / enclosing-diagonal^2."""
    boxes1 = jnp.asarray(boxes1, dtype=jnp.float32).reshape(-1, 4)
    boxes2 = jnp.asarray(boxes2, dtype=jnp.float32).reshape(-1, 4)
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / (union + _EPS)
    diag, dist = _diag_and_center_dist(boxes1, boxes2)
    return iou - dist / diag


def _diag_and_center_dist(boxes1: Array, boxes2: Array):
    lt = jnp.minimum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.maximum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    diag = wh[..., 0] ** 2 + wh[..., 1] ** 2 + _EPS
    c1 = (boxes1[:, :2] + boxes1[:, 2:]) / 2
    c2 = (boxes2[:, :2] + boxes2[:, 2:]) / 2
    d = c1[:, None, :] - c2[None, :, :]
    dist = d[..., 0] ** 2 + d[..., 1] ** 2
    return diag, dist


def complete_box_iou(boxes1: Array, boxes2: Array) -> Array:
    """CIoU: DIoU - aspect-ratio penalty alpha*v."""
    boxes1 = jnp.asarray(boxes1, dtype=jnp.float32).reshape(-1, 4)
    boxes2 = jnp.asarray(boxes2, dtype=jnp.float32).reshape(-1, 4)
    inter, union = _inter_union(boxes1, boxes2)
    iou = inter / (union + _EPS)
    diag, dist = _diag_and_center_dist(boxes1, boxes2)
    diou = iou - dist / diag

    w1 = boxes1[:, 2] - boxes1[:, 0]
    h1 = boxes1[:, 3] - boxes1[:, 1]
    w2 = boxes2[:, 2] - boxes2[:, 0]
    h2 = boxes2[:, 3] - boxes2[:, 1]
    v = (4 / jnp.pi**2) * (
        jnp.arctan(w2 / (h2 + _EPS))[None, :] - jnp.arctan(w1 / (h1 + _EPS))[:, None]
    ) ** 2
    alpha = v / (1 - iou + v + _EPS)
    return diou - alpha * v


def _iou_family(pairwise_fn, preds, target, iou_threshold, replacement_val, aggregate):
    preds = jnp.asarray(preds, dtype=jnp.float32).reshape(-1, 4)
    target = jnp.asarray(target, dtype=jnp.float32).reshape(-1, 4)
    iou = pairwise_fn(preds, target)
    if iou_threshold is not None:
        iou = jnp.where(iou < iou_threshold, replacement_val, iou)
    if not aggregate:
        return iou
    if iou.size == 0:
        return jnp.asarray(0.0)
    n = min(iou.shape[0], iou.shape[1])
    return jnp.mean(jnp.diagonal(iou)[:n])


def intersection_over_union(
    preds: Array,
    target: Array,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> Array:
    """Pairwise (or matched-mean) IoU (reference functional/detection/iou.py:41-95).

    Example:
        >>> from torchmetrics_tpu.functional import intersection_over_union
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = intersection_over_union(preds, target)
        >>> round(float(result), 4)
        -0.0
    """
    return _iou_family(box_iou, preds, target, iou_threshold, replacement_val, aggregate)


def generalized_intersection_over_union(
    preds: Array,
    target: Array,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> Array:
    """generalized intersection over union (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import generalized_intersection_over_union
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = generalized_intersection_over_union(preds, target)
        >>> round(float(result), 4)
        -19400000.0
    """

    return _iou_family(generalized_box_iou, preds, target, iou_threshold, replacement_val, aggregate)


def distance_intersection_over_union(
    preds: Array,
    target: Array,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> Array:
    """distance intersection over union (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import distance_intersection_over_union
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = distance_intersection_over_union(preds, target)
        >>> round(float(result), 4)
        -0.1206
    """

    return _iou_family(distance_box_iou, preds, target, iou_threshold, replacement_val, aggregate)


def complete_intersection_over_union(
    preds: Array,
    target: Array,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> Array:
    """complete intersection over union (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import complete_intersection_over_union
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = complete_intersection_over_union(preds, target)
        >>> round(float(result), 4)
        -1.9606
    """

    return _iou_family(complete_box_iou, preds, target, iou_threshold, replacement_val, aggregate)
