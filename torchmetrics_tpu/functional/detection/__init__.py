from torchmetrics_tpu.functional.detection.iou import (  # noqa: F401
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
)
from torchmetrics_tpu.functional.detection.panoptic_quality import (  # noqa: F401
    modified_panoptic_quality,
    panoptic_quality,
)

__all__ = [
    "complete_intersection_over_union",
    "distance_intersection_over_union",
    "generalized_intersection_over_union",
    "intersection_over_union",
    "modified_panoptic_quality",
    "panoptic_quality",
]
