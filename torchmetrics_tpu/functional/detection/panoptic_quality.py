"""Panoptic quality (reference functional/detection/_panoptic_quality_common.py +
panoptic_qualities.py).

Redesign: the reference builds Python dicts keyed by (category, instance) "colors"
and loops over every segment pair. Here segments are relabelled with ``np.unique``
and ALL pairwise statistics (areas, intersections, IoU, matching, FP/FN filters)
are dense vectorized array ops over the (num_pred_segments, num_target_segments)
grid — no per-segment Python loop. Segment extraction is host-side (as the
reference's dicts are); the per-category accumulators are device arrays.
"""
from __future__ import annotations

from typing import Collection, Dict, Optional, Set, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array


def _parse_categories(things: Collection[int], stuffs: Collection[int]) -> Tuple[Set[int], Set[int]]:
    """Validate and normalize category sets (reference _panoptic_quality_common.py:65-93)."""
    things_parsed = set(things)
    stuffs_parsed = set(stuffs)
    if not all(isinstance(t, int) or hasattr(t, "item") for t in things_parsed | stuffs_parsed):
        raise TypeError("Expected arguments `things` and `stuffs` to contain `int` categories")
    things_parsed = {int(t) for t in things_parsed}
    stuffs_parsed = {int(s) for s in stuffs_parsed}
    if things_parsed & stuffs_parsed:
        raise ValueError(
            f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things} and {stuffs}"
        )
    if not (things_parsed | stuffs_parsed):
        raise ValueError("At least one of `things` and `stuffs` must be non-empty.")
    return things_parsed, stuffs_parsed


def _get_void_color(things: Set[int], stuffs: Set[int]) -> Tuple[int, int]:
    unused_category_id = 1 + max([0, *list(things), *list(stuffs)])
    return unused_category_id, 0


def _get_category_id_to_continuous_id(things: Set[int], stuffs: Set[int]) -> Dict[int, int]:
    thing_id_to_continuous_id = {t: idx for idx, t in enumerate(sorted(things))}
    stuff_id_to_continuous_id = {s: idx + len(things) for idx, s in enumerate(sorted(stuffs))}
    return {**thing_id_to_continuous_id, **stuff_id_to_continuous_id}


def _validate_inputs(preds, target) -> None:
    preds = np.asarray(preds)
    target = np.asarray(target)
    if preds.shape != target.shape:
        raise ValueError(f"Expected argument `preds` and `target` to have the same shape, got {preds.shape} and {target.shape}")
    if preds.ndim < 3:
        raise ValueError(f"Expected argument `preds` to have at least 3 dimensions, got {preds.ndim}")
    if preds.shape[-1] != 2:
        raise ValueError(f"Expected the final dimension of `preds` to be of size 2, got {preds.shape[-1]}")


def _preprocess_inputs(
    things: Set[int],
    stuffs: Set[int],
    inputs,
    void_color: Tuple[int, int],
    allow_unknown_category: bool,
) -> np.ndarray:
    """Flatten spatial dims; zero stuff instance ids; map unknowns to void."""
    out = np.array(inputs, dtype=np.int64, copy=True).reshape(inputs.shape[0], -1, 2)
    cats = out[:, :, 0]
    mask_stuffs = np.isin(cats, list(stuffs))
    mask_things = np.isin(cats, list(things))
    out[:, :, 1] = np.where(mask_stuffs, 0, out[:, :, 1])
    known = mask_things | mask_stuffs
    if not allow_unknown_category and not known.all():
        raise ValueError(f"Unknown categories found: {np.unique(cats[~known])}")
    out[~known] = np.asarray(void_color, dtype=np.int64)
    return out


def _panoptic_quality_update_sample(
    preds: np.ndarray,
    target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    stuffs_modified_metric: Optional[Set[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Stat scores for one sample, fully vectorized over segment pairs.

    Matches reference _panoptic_quality_update_sample (:312-394): IoU uses
    void-corrected unions; things match at IoU > 0.5; modified-PQ stuffs
    accumulate IoU > 0 with TP = number of target segments; FP/FN filters drop
    segments that are mostly void.
    """
    stuffs_modified_metric = stuffs_modified_metric or set()
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories, dtype=np.float64)
    tp = np.zeros(num_categories, dtype=np.int64)
    fp = np.zeros(num_categories, dtype=np.int64)
    fn = np.zeros(num_categories, dtype=np.int64)

    up, pinv = np.unique(preds, axis=0, return_inverse=True)  # (P_seg, 2)
    ut, tinv = np.unique(target, axis=0, return_inverse=True)  # (T_seg, 2)
    n_p, n_t = len(up), len(ut)
    pred_areas = np.bincount(pinv, minlength=n_p).astype(np.float64)
    target_areas = np.bincount(tinv, minlength=n_t).astype(np.float64)
    inter = np.bincount(pinv * n_t + tinv, minlength=n_p * n_t).reshape(n_p, n_t).astype(np.float64)

    void = np.asarray(void_color, dtype=np.int64)
    p_is_void = (up == void).all(axis=1)
    t_is_void = (ut == void).all(axis=1)
    pred_void = inter[:, t_is_void].sum(axis=1)  # area of each pred segment overlapping void target
    void_target = inter[p_is_void, :].sum(axis=0)  # area of each target segment overlapping void pred

    union = pred_areas[:, None] - pred_void[:, None] + target_areas[None, :] - void_target[None, :] - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        iou = np.where(inter > 0, inter / union, 0.0)

    cat_match = up[:, 0:1] == ut[None, :, 0]  # (P_seg, T_seg)
    considered = cat_match & (inter > 0) & ~t_is_void[None, :] & ~p_is_void[:, None]

    cont_id_t = np.array([cat_id_to_continuous_id.get(int(c), -1) for c in ut[:, 0]])
    t_modified = np.isin(ut[:, 0], list(stuffs_modified_metric)) if stuffs_modified_metric else np.zeros(n_t, bool)
    p_modified = np.isin(up[:, 0], list(stuffs_modified_metric)) if stuffs_modified_metric else np.zeros(n_p, bool)

    # things (and plain-PQ stuffs): match at IoU > 0.5 — at most one per row/col
    matched = considered & (iou > 0.5) & ~t_modified[None, :]
    pair_p, pair_t = np.nonzero(matched)
    np.add.at(iou_sum, cont_id_t[pair_t], iou[pair_p, pair_t])
    np.add.at(tp, cont_id_t[pair_t], 1)

    # modified-PQ stuffs: accumulate every IoU > 0; TP = number of target segments
    mod_pairs = considered & (iou > 0) & t_modified[None, :]
    mp, mt = np.nonzero(mod_pairs)
    np.add.at(iou_sum, cont_id_t[mt], iou[mp, mt])
    mod_targets = ~t_is_void & t_modified
    np.add.at(tp, cont_id_t[mod_targets], 1)

    # FN: unmatched non-void target segments not mostly void
    t_matched = matched.any(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        t_void_frac = np.where(target_areas > 0, void_target / target_areas, 0.0)
    fns = ~t_matched & ~t_is_void & ~t_modified & (t_void_frac <= 0.5)
    np.add.at(fn, cont_id_t[fns], 1)

    # FP: unmatched non-void pred segments not mostly void
    p_matched = matched.any(axis=1)
    with np.errstate(divide="ignore", invalid="ignore"):
        p_void_frac = np.where(pred_areas > 0, pred_void / pred_areas, 0.0)
    cont_id_p = np.array([cat_id_to_continuous_id.get(int(c), -1) for c in up[:, 0]])
    fps = ~p_matched & ~p_is_void & ~p_modified & (p_void_frac <= 0.5) & (cont_id_p >= 0)
    np.add.at(fp, cont_id_p[fps], 1)

    return iou_sum, tp, fp, fn


def _panoptic_quality_update(
    flatten_preds: np.ndarray,
    flatten_target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    modified_metric_stuffs: Optional[Set[int]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Batch stat scores: samples are independent (segments never match across frames)."""
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories, dtype=np.float64)
    tp = np.zeros(num_categories, dtype=np.int64)
    fp = np.zeros(num_categories, dtype=np.int64)
    fn = np.zeros(num_categories, dtype=np.int64)
    for p, t in zip(flatten_preds, flatten_target):
        r = _panoptic_quality_update_sample(p, t, cat_id_to_continuous_id, void_color, modified_metric_stuffs)
        iou_sum += r[0]
        tp += r[1]
        fp += r[2]
        fn += r[3]
    return jnp.asarray(iou_sum), jnp.asarray(tp), jnp.asarray(fp), jnp.asarray(fn)


def _panoptic_quality_compute(
    iou_sum: Array, true_positives: Array, false_positives: Array, false_negatives: Array
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Per-class and averaged PQ/SQ/RQ (reference _panoptic_quality_common.py:447-476)."""
    sq = jnp.where(true_positives > 0.0, iou_sum / jnp.clip(true_positives, 1), 0.0)
    denominator = true_positives + 0.5 * false_positives + 0.5 * false_negatives
    rq = jnp.where(denominator > 0.0, true_positives / jnp.clip(denominator, 1e-12), 0.0)
    pq = sq * rq
    seen = denominator > 0
    pq_avg = jnp.mean(pq[seen]) if bool(jnp.any(seen)) else jnp.asarray(jnp.nan)
    sq_avg = jnp.mean(sq[seen]) if bool(jnp.any(seen)) else jnp.asarray(jnp.nan)
    rq_avg = jnp.mean(rq[seen]) if bool(jnp.any(seen)) else jnp.asarray(jnp.nan)
    return pq, sq, rq, pq_avg, sq_avg, rq_avg


def panoptic_quality(
    preds: Array,
    target: Array,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
    return_sq_and_rq: bool = False,
    return_per_class: bool = False,
) -> Array:
    """Functional PQ over ``(B, *spatial, 2)`` (category, instance) maps.

    Example:
        >>> from torchmetrics_tpu.functional import panoptic_quality
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])
        >>> target = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [0, 0], [1, 0]]])
        >>> result = panoptic_quality(preds, target, things={0}, stuffs={1})
        >>> round(float(result), 4)
        0.5
    """
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(np.asarray(preds), np.asarray(target))
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, np.asarray(preds), void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, np.asarray(target), void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(flatten_preds, flatten_target, cat_id_to_continuous_id, void_color)
    pq, sq, rq, pq_avg, sq_avg, rq_avg = _panoptic_quality_compute(iou_sum, tp, fp, fn)
    if return_per_class:
        if return_sq_and_rq:
            return jnp.stack((pq, sq, rq), axis=-1)
        return pq.reshape(1, -1)
    if return_sq_and_rq:
        return jnp.stack((pq_avg, sq_avg, rq_avg))
    return pq_avg


def modified_panoptic_quality(
    preds: Array,
    target: Array,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    """Modified PQ: stuff classes score mean IoU over all overlaps (reference panoptic_qualities.py:182+).

    Example:
        >>> from torchmetrics_tpu.functional import modified_panoptic_quality
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])
        >>> target = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [0, 0], [1, 0]]])
        >>> result = modified_panoptic_quality(preds, target, things={0}, stuffs={1})
        >>> round(float(result), 4)
        0.625
    """
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(np.asarray(preds), np.asarray(target))
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, np.asarray(preds), void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, np.asarray(target), void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(
        flatten_preds, flatten_target, cat_id_to_continuous_id, void_color, modified_metric_stuffs=stuffs
    )
    _, _, _, pq_avg, _, _ = _panoptic_quality_compute(iou_sum, tp, fp, fn)
    return pq_avg
