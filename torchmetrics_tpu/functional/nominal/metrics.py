"""Nominal-association metrics (reference functional/nominal/*.py).

The chi-square-on-confusion-matrix family (Cramer's V, Tschuprow's T,
Pearson's contingency coefficient, Theil's U) plus Fleiss kappa, with the
pairwise ``*_matrix`` batch variants. Confusion matrices are built with the
same bincount trick the classification suite uses.
"""
from __future__ import annotations

import itertools
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.prints import rank_zero_warn


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[float]) -> None:
    if nan_strategy not in ["replace", "drop"]:
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (float, int)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _handle_nan_in_data(
    preds: Array, target: Array, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Tuple[Array, Array, Array]:
    """NaN handling returning a static-shape (preds, target, valid-weight) triple.

    The reference's 'drop' physically removes rows (dynamic shape); here dropped
    rows get zero weight so the whole update stays jit-traceable.
    """
    if nan_strategy == "replace":
        return (
            jnp.nan_to_num(preds, nan=nan_replace_value),
            jnp.nan_to_num(target, nan=nan_replace_value),
            jnp.ones(preds.shape, dtype=bool),
        )
    valid = ~(jnp.isnan(preds) | jnp.isnan(target))
    return jnp.nan_to_num(preds, nan=0.0), jnp.nan_to_num(target, nan=0.0), valid


def _nominal_confmat_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Confusion matrix with fixed ``num_classes`` (modular state path).

    Values must already lie in [0, num_classes); validated eagerly (a traced
    update cannot raise on data, mirroring every other jit-safe update here).
    """
    import jax

    from torchmetrics_tpu.functional.classification.confusion_matrix import (
        _multiclass_confusion_matrix_update,
    )

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    # NaNs are impossible in integer inputs, so keep integer labels in the integer
    # path — a float32 round-trip would corrupt label values above 2**24
    if jnp.issubdtype(preds.dtype, jnp.floating) or jnp.issubdtype(target.dtype, jnp.floating):
        preds = preds.astype(jnp.float32)
        target = target.astype(jnp.float32)
        preds, target, valid = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    else:
        valid = jnp.ones(preds.shape, dtype=bool)
    if not isinstance(preds, jax.core.Tracer):
        vals = jnp.concatenate([preds[valid], target[valid]])
        if vals.size and (bool(vals.min() < 0) or bool(vals.max() >= num_classes)):
            raise ValueError(
                f"Expected label values in [0, {num_classes}), but got values in"
                f" [{float(vals.min())}, {float(vals.max())}]. Relabel the data or raise `num_classes`."
            )
    target_i = jnp.where(valid, target, 0).astype(jnp.int32)
    return _multiclass_confusion_matrix_update(preds, target_i, valid, num_classes)


def _nominal_confmat_from_values(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Confusion matrix over ARBITRARY label values (functional path).

    Joint unique-relabel makes non-contiguous / non-zero-based labels work —
    the count of distinct values and the index space then coincide.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    # integer labels stay integer (no NaNs possible; float32 loses precision > 2**24)
    if jnp.issubdtype(preds.dtype, jnp.floating) or jnp.issubdtype(target.dtype, jnp.floating):
        preds, target, valid = _handle_nan_in_data(
            preds.astype(jnp.float32), target.astype(jnp.float32), nan_strategy, nan_replace_value
        )
        preds, target = preds[valid], target[valid]
    uniques = jnp.unique(jnp.concatenate([preds, target]))
    preds_idx = jnp.searchsorted(uniques, preds)
    target_idx = jnp.searchsorted(uniques, target)
    num_classes = int(uniques.shape[0])
    idx = (target_idx * num_classes + preds_idx).reshape(-1)
    return jnp.bincount(idx, length=num_classes * num_classes).reshape(num_classes, num_classes)


def _reduced_stats(confmat: Array):
    """Chi-square ingredients on the full matrix, zero rows/cols masked.

    The reference physically drops empty rows/columns (nominal/utils.py
    _drop_empty_rows_and_cols) — a dynamic shape, illegal under jit. All-zero
    rows/cols contribute nothing to chi-square, so the same numbers fall out of
    masked full-matrix reductions with TRACED effective row/col counts.
    """
    confmat = confmat.astype(jnp.float32)
    rows = confmat.sum(1)
    cols = confmat.sum(0)
    num_rows = jnp.sum(rows != 0)
    num_cols = jnp.sum(cols != 0)
    total = confmat.sum()
    expected = jnp.einsum("r,c->rc", rows, cols) / total
    return confmat, expected, num_rows.astype(jnp.float32), num_cols.astype(jnp.float32), total


def _compute_chi_squared_masked(confmat: Array, expected: Array, num_rows, num_cols, bias_correction: bool) -> Array:
    """Chi-square test of independence (reference nominal/utils.py, after scipy)."""
    df = num_rows * num_cols - num_rows - num_cols + 1
    if bias_correction:
        diff = expected - confmat
        direction = jnp.sign(diff)
        corrected = confmat + direction * jnp.minimum(0.5, jnp.abs(direction))
        confmat = jnp.where(df == 1, corrected, confmat)
    chi = jnp.sum(jnp.where(expected > 0, (confmat - expected) ** 2 / jnp.where(expected > 0, expected, 1.0), 0.0))
    return jnp.where(df == 0, 0.0, chi)


def _compute_bias_corrected_values(
    phi_squared: Array, num_rows: Array, num_cols: Array, confmat_sum: Array
) -> Tuple[Array, Array, Array]:
    phi_squared_corrected = jnp.maximum(
        0.0, phi_squared - ((num_rows - 1) * (num_cols - 1)) / (confmat_sum - 1)
    )
    rows_corrected = num_rows - (num_rows - 1) ** 2 / (confmat_sum - 1)
    cols_corrected = num_cols - (num_cols - 1) ** 2 / (confmat_sum - 1)
    return phi_squared_corrected, rows_corrected, cols_corrected


def _bias_correction_warning_if_concrete(cond: Array, metric_name: str) -> None:
    import jax

    if not isinstance(cond, jax.core.Tracer) and bool(cond):
        rank_zero_warn(
            f"Unable to compute {metric_name} using bias correction. Please consider to set `bias_correction=False`.",
            UserWarning,
        )


def _cramers_v_compute(confmat: Array, bias_correction: bool) -> Array:
    confmat, expected, num_rows, num_cols, cm_sum = _reduced_stats(confmat)
    chi_squared = _compute_chi_squared_masked(confmat, expected, num_rows, num_cols, bias_correction)
    phi_squared = chi_squared / cm_sum
    if bias_correction:
        phi_sq_c, rows_c, cols_c = _compute_bias_corrected_values(phi_squared, num_rows, num_cols, cm_sum)
        unusable = jnp.minimum(rows_c, cols_c) == 1
        _bias_correction_warning_if_concrete(unusable, "Cramer's V")
        value = jnp.sqrt(phi_sq_c / jnp.clip(jnp.minimum(rows_c - 1, cols_c - 1), 1e-12))
        return jnp.where(unusable, jnp.nan, jnp.clip(value, 0.0, 1.0))
    value = jnp.sqrt(phi_squared / jnp.clip(jnp.minimum(num_rows - 1, num_cols - 1), 1e-12))
    return jnp.clip(value, 0.0, 1.0)


def cramers_v(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Cramer's V: sqrt(phi^2 / min(r-1, k-1)).

    Example:
        >>> from torchmetrics_tpu.functional import cramers_v
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> result = cramers_v(preds, target)
        >>> round(float(result), 4)
        0.6667
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_confmat_from_values(preds, target, nan_strategy, nan_replace_value)
    return _cramers_v_compute(confmat, bias_correction)


def _tschuprows_t_compute(confmat: Array, bias_correction: bool) -> Array:
    confmat, expected, num_rows, num_cols, cm_sum = _reduced_stats(confmat)
    chi_squared = _compute_chi_squared_masked(confmat, expected, num_rows, num_cols, bias_correction)
    phi_squared = chi_squared / cm_sum
    if bias_correction:
        phi_sq_c, rows_c, cols_c = _compute_bias_corrected_values(phi_squared, num_rows, num_cols, cm_sum)
        unusable = jnp.minimum(rows_c, cols_c) == 1
        _bias_correction_warning_if_concrete(unusable, "Tschuprow's T")
        value = jnp.sqrt(phi_sq_c / jnp.clip(jnp.sqrt((rows_c - 1) * (cols_c - 1)), 1e-12))
        return jnp.where(unusable, jnp.nan, jnp.clip(value, 0.0, 1.0))
    value = jnp.sqrt(phi_squared / jnp.clip(jnp.sqrt((num_rows - 1.0) * (num_cols - 1.0)), 1e-12))
    return jnp.clip(value, 0.0, 1.0)


def tschuprows_t(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Tschuprow's T: sqrt(phi^2 / sqrt((r-1)(k-1))).

    Example:
        >>> from torchmetrics_tpu.functional import tschuprows_t
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> result = tschuprows_t(preds, target)
        >>> round(float(result), 4)
        0.6667
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_confmat_from_values(preds, target, nan_strategy, nan_replace_value)
    return _tschuprows_t_compute(confmat, bias_correction)


def _pearsons_contingency_coefficient_compute(confmat: Array) -> Array:
    confmat, expected, num_rows, num_cols, cm_sum = _reduced_stats(confmat)
    chi_squared = _compute_chi_squared_masked(confmat, expected, num_rows, num_cols, bias_correction=False)
    phi_squared = chi_squared / cm_sum
    return jnp.clip(jnp.sqrt(phi_squared / (1 + phi_squared)), 0.0, 1.0)


def pearsons_contingency_coefficient(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pearson's contingency coefficient: sqrt(phi^2 / (1 + phi^2)).

    Example:
        >>> from torchmetrics_tpu.functional import pearsons_contingency_coefficient
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> result = pearsons_contingency_coefficient(preds, target)
        >>> round(float(result), 4)
        0.7559
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_confmat_from_values(preds, target, nan_strategy, nan_replace_value)
    return _pearsons_contingency_coefficient_compute(confmat)


def _conditional_entropy_compute(confmat: Array) -> Array:
    total = confmat.sum()
    p_xy = confmat / total
    p_y = confmat.sum(1) / total
    ratio = jnp.where(p_xy > 0, p_y[:, None] / jnp.where(p_xy > 0, p_xy, 1.0), 1.0)
    return jnp.sum(jnp.where(p_xy > 0, p_xy * jnp.log(ratio), 0.0))


def _theils_u_compute(confmat: Array) -> Array:
    # zero rows/cols contribute nothing to either entropy: masked sums replace
    # the reference's dynamic-shape row/col dropping
    confmat = confmat.astype(jnp.float32)
    s_xy = _conditional_entropy_compute(confmat)
    total = confmat.sum()
    p_x = confmat.sum(0) / total
    s_x = -jnp.sum(jnp.where(p_x > 0, p_x * jnp.log(jnp.where(p_x > 0, p_x, 1.0)), 0.0))
    return jnp.where(s_x == 0, 0.0, (s_x - s_xy) / jnp.where(s_x == 0, 1.0, s_x))


def theils_u(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Theil's U (uncertainty coefficient): (H(X) - H(X|Y)) / H(X). Asymmetric.

    Example:
        >>> from torchmetrics_tpu.functional import theils_u
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> result = theils_u(preds, target)
        >>> round(float(result), 4)
        0.7103
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_confmat_from_values(preds, target, nan_strategy, nan_replace_value)
    return _theils_u_compute(confmat)


def _matrix_variant(pair_fn, matrix: Array, symmetric: bool, **kwargs) -> Array:
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = jnp.ones((num_variables, num_variables))
    for i, j in itertools.combinations(range(num_variables), 2):
        x, y = matrix[:, i], matrix[:, j]
        if symmetric:
            v = pair_fn(x, y, **kwargs)
            out = out.at[i, j].set(v).at[j, i].set(v)
        else:
            out = out.at[i, j].set(pair_fn(x, y, **kwargs)).at[j, i].set(pair_fn(y, x, **kwargs))
    return out


def cramers_v_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Cramer's V over feature columns.

    Example:
        >>> from torchmetrics_tpu.functional import cramers_v_matrix
        >>> import jax.numpy as jnp
        >>> matrix = jnp.asarray([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> result = cramers_v_matrix(matrix)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 0.0], [0.0, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    return _matrix_variant(
        cramers_v, matrix, True, bias_correction=bias_correction, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value
    )


def tschuprows_t_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Tschuprow's T over feature columns.

    Example:
        >>> from torchmetrics_tpu.functional import tschuprows_t_matrix
        >>> import jax.numpy as jnp
        >>> matrix = jnp.asarray([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> result = tschuprows_t_matrix(matrix)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 0.0], [0.0, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    return _matrix_variant(
        tschuprows_t, matrix, True, bias_correction=bias_correction, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value
    )


def pearsons_contingency_coefficient_matrix(
    matrix: Array, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Pairwise Pearson contingency coefficient over feature columns.

    Example:
        >>> from torchmetrics_tpu.functional import pearsons_contingency_coefficient_matrix
        >>> import jax.numpy as jnp
        >>> matrix = jnp.asarray([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> result = pearsons_contingency_coefficient_matrix(matrix)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 0.5773999691009521], [0.5773999691009521, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    return _matrix_variant(
        pearsons_contingency_coefficient, matrix, True, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value
    )


def theils_u_matrix(
    matrix: Array, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Pairwise (asymmetric) Theil's U over feature columns.

    Example:
        >>> from torchmetrics_tpu.functional import theils_u_matrix
        >>> import jax.numpy as jnp
        >>> matrix = jnp.asarray([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> result = theils_u_matrix(matrix)
        >>> jnp.round(result, 4).tolist()
        [[1.0, 0.36910000443458557], [0.36910000443458557, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    return _matrix_variant(theils_u, matrix, False, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value)


def _fleiss_kappa_update(ratings: Array, mode: str = "counts") -> Array:
    ratings = jnp.asarray(ratings)
    if mode == "probs":
        if ratings.ndim != 3 or not jnp.issubdtype(ratings.dtype, jnp.floating):
            raise ValueError(
                "If argument ``mode`` is 'probs', ratings must have 3 dimensions with the format"
                " [n_samples, n_categories, n_raters] and be floating point."
            )
        num_categories = ratings.shape[1]
        winners = ratings.argmax(axis=1)  # (n_samples, n_raters)
        one_hot = jax_one_hot(winners, num_categories)
        return one_hot.sum(axis=1)  # (n_samples, n_categories)
    if ratings.ndim != 2 or jnp.issubdtype(ratings.dtype, jnp.floating):
        raise ValueError(
            "If argument ``mode`` is `counts`, ratings must have 2 dimensions with the format"
            " [n_samples, n_categories] and be none floating point."
        )
    return ratings


def jax_one_hot(x: Array, num_classes: int) -> Array:
    return (x[..., None] == jnp.arange(num_classes)).astype(jnp.int32)


def _fleiss_kappa_compute(counts: Array) -> Array:
    counts = counts.astype(jnp.float32)
    total = counts.shape[0]
    num_raters = counts.sum(1).max()
    p_i = counts.sum(axis=0) / (total * num_raters)
    p_j = ((counts**2).sum(axis=1) - num_raters) / (num_raters * (num_raters - 1))
    p_bar = p_j.mean()
    pe_bar = (p_i**2).sum()
    return (p_bar - pe_bar) / (1 - pe_bar + 1e-5)


def fleiss_kappa(ratings: Array, mode: str = "counts") -> Array:
    """Fleiss kappa inter-rater agreement over a [n_samples, n_categories] counts matrix.

    Example:
        >>> from torchmetrics_tpu.functional import fleiss_kappa
        >>> import jax.numpy as jnp
        >>> ratings = jnp.asarray([[2, 1, 0], [1, 2, 0], [0, 1, 2], [3, 0, 0]])
        >>> result = fleiss_kappa(ratings)
        >>> round(float(result), 4)
        0.1818
    """
    if mode not in ["counts", "probs"]:
        raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'.")
    counts = _fleiss_kappa_update(ratings, mode)
    return _fleiss_kappa_compute(counts)
