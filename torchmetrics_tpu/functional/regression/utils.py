"""Shared regression helpers (reference functional/regression/utils.py)."""
from __future__ import annotations

from jax import Array

from torchmetrics_tpu.utils.compute import _at_least_float32  # noqa: F401  (canonical home: utils.compute)



def _check_data_shape_to_num_outputs(
    preds: Array, target: Array, num_outputs: int, allow_1d_reshape: bool = False
) -> None:
    """Check shapes are consistent with ``num_outputs`` (reference utils.py:20-43)."""
    if preds.ndim > 2 or target.ndim > 2:
        raise ValueError(
            f"Expected both predictions and target to be either 1- or 2-dimensional tensors,"
            f" but got {target.ndim} and {preds.ndim}."
        )
    cond1 = False if allow_1d_reshape else (num_outputs == 1 and not (preds.ndim == 1 or preds.shape[1] == 1))
    cond2 = num_outputs > 1 and (preds.ndim < 2 or num_outputs != preds.shape[1])
    if cond1 or cond2:
        raise ValueError(
            f"Expected argument `num_outputs` to match the second dimension of input, but got {num_outputs}"
            f" and {preds.shape[1] if preds.ndim > 1 else 1}."
        )
