"""CosineSimilarity and KLDivergence (reference functional/regression/{cosine_similarity,kl_divergence}.py)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _safe_xlogy


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot = (preds * target).sum(-1)
    norm = jnp.linalg.norm(preds, axis=-1) * jnp.linalg.norm(target, axis=-1)
    sim = dot / norm
    if reduction == "sum":
        return sim.sum()
    if reduction == "mean":
        return sim.mean()
    if reduction in ("none", None):
        return sim
    raise ValueError(f"Expected reduction to be one of `['sum', 'mean', 'none', None]` but got {reduction}")


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """cosine similarity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import cosine_similarity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0], [0.0, 1.0, 0.5]])
        >>> target = jnp.asarray([[1.0, 2.0, 2.5], [0.0, 1.0, 1.0]])
        >>> result = cosine_similarity(preds, target)
        >>> round(float(result), 4)
        1.9447
    """

    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 2:
        raise ValueError(f"Expected input to cosine similarity to be 2D tensors of shape `[N,D]` but got {preds.shape}")
    return _cosine_similarity_compute(preds, target, reduction)


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, int]:
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")
    total = p.shape[0]
    if log_prob:
        measures = (jnp.exp(p) * (p - q)).sum(-1)
    else:
        p = p / p.sum(-1, keepdims=True)
        q = q / q.sum(-1, keepdims=True)
        measures = _safe_xlogy(p, p / q).sum(-1)
    return measures, total


def _kld_compute(measures: Array, total: Array, reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return measures.sum()
    if reduction == "mean":
        return measures.sum() / total
    if reduction in ("none", None):
        return measures
    return measures / total


def kl_divergence(p: Array, q: Array, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """KL(P‖Q) (reference kl_divergence.py).

    Example:
        >>> from torchmetrics_tpu.functional import kl_divergence
        >>> import jax.numpy as jnp
        >>> p = jnp.asarray([[0.3, 0.3, 0.4]])
        >>> q = jnp.asarray([[0.25, 0.5, 0.25]])
        >>> result = kl_divergence(p, q)
        >>> round(float(result), 4)
        0.0895
    """
    measures, total = _kld_update(jnp.asarray(p, dtype=jnp.float32), jnp.asarray(q, dtype=jnp.float32), log_prob)
    return _kld_compute(measures, total, reduction)
