"""Elementary error metrics: MAE, MSE, MSLE, MAPE, SMAPE, WMAPE, RSE, LogCosh,
MinkowskiDistance, TweedieDevianceScore, CriticalSuccessIndex.

Reference: functional/regression/{mae,mse,log_mse,mape,symmetric_mape,wmape,rse,
log_cosh,minkowski,tweedie_deviance,csi}.py — each decomposed into
``_update`` (sum + count states) and ``_compute`` (safe divide).
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _safe_divide, _safe_xlogy
from torchmetrics_tpu.functional.regression.utils import _at_least_float32


# ------------------------------------------------------------------------ MAE
def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    return jnp.abs(preds - target).sum(), preds.size


def _mean_absolute_error_compute(sum_abs_error: Array, num_obs: Union[int, Array]) -> Array:
    return sum_abs_error / num_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """mean absolute error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import mean_absolute_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = mean_absolute_error(preds, target)
        >>> round(float(result), 4)
        0.5
    """

    sum_abs_error, num_obs = _mean_absolute_error_update(jnp.asarray(preds), jnp.asarray(target))
    return _mean_absolute_error_compute(sum_abs_error, num_obs)


# ------------------------------------------------------------------------ MSE
def _mean_squared_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    diff = preds - target
    sum_squared_error = (diff * diff).sum(0) if num_outputs > 1 else (diff * diff).sum()
    return sum_squared_error, target.shape[0] if num_outputs > 1 else target.size


def _mean_squared_error_compute(sum_squared_error: Array, num_obs: Union[int, Array], squared: bool = True) -> Array:
    mse = sum_squared_error / num_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds: Array, target: Array, squared: bool = True, num_outputs: int = 1) -> Array:
    """MSE (or RMSE with ``squared=False``); reference functional/regression/mse.py.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_squared_error
        >>> round(float(mean_squared_error(jnp.asarray([1., 2., 3.]), jnp.asarray([1., 2., 5.]))), 4)
        1.3333
    """
    sum_squared_error, num_obs = _mean_squared_error_update(jnp.asarray(preds), jnp.asarray(target), num_outputs)
    return _mean_squared_error_compute(sum_squared_error, num_obs, squared)


# ----------------------------------------------------------------------- MSLE
def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    sum_squared_log_error = ((jnp.log1p(preds) - jnp.log1p(target)) ** 2).sum()
    return sum_squared_log_error, preds.size


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """mean squared log error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import mean_squared_log_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = mean_squared_log_error(preds, target)
        >>> round(float(result), 4)
        0.128
    """

    s, n = _mean_squared_log_error_update(jnp.asarray(preds), jnp.asarray(target))
    return s / n


# ----------------------------------------------------------------------- MAPE
def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), min=epsilon)
    return abs_per_error.sum(), preds.size


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """mean absolute percentage error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import mean_absolute_percentage_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = mean_absolute_percentage_error(preds, target)
        >>> round(float(result), 4)
        0.3274
    """

    s, n = _mean_absolute_percentage_error_update(jnp.asarray(preds), jnp.asarray(target))
    return s / n


# ---------------------------------------------------------------------- SMAPE
def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), min=epsilon)
    return 2 * abs_per_error.sum(), preds.size


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """symmetric mean absolute percentage error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import symmetric_mean_absolute_percentage_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = symmetric_mean_absolute_percentage_error(preds, target)
        >>> round(float(result), 4)
        0.5788
    """

    s, n = _symmetric_mean_absolute_percentage_error_update(
        jnp.asarray(preds), jnp.asarray(target)
    )
    return s / n


# ---------------------------------------------------------------------- WMAPE
def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    return jnp.abs(preds - target).sum(), jnp.abs(target).sum()


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """weighted mean absolute percentage error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import weighted_mean_absolute_percentage_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = weighted_mean_absolute_percentage_error(preds, target)
        >>> round(float(result), 4)
        0.16
    """

    s, t = _weighted_mean_absolute_percentage_error_update(
        jnp.asarray(preds), jnp.asarray(target)
    )
    return s / jnp.clip(t, min=1.17e-06)


# ------------------------------------------------------------------------ RSE
def _relative_squared_error_compute(
    sum_squared_obs: Array, sum_obs: Array, sum_squared_error: Array, num_obs: Union[int, Array], squared: bool = True
) -> Array:
    """RSE = Σ(y−ŷ)² / Σ(y−ȳ)² (reference rse.py)."""
    denom = sum_squared_obs - sum_obs * sum_obs / num_obs
    rse = sum_squared_error / denom
    if not squared:
        rse = jnp.sqrt(rse)
    return rse.mean()


def relative_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """relative squared error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import relative_squared_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = relative_squared_error(preds, target)
        >>> round(float(result), 4)
        0.0514
    """

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    sum_squared_obs = (target * target).sum(0)
    sum_obs = target.sum(0)
    sum_squared_error = ((target - preds) ** 2).sum(0)
    return _relative_squared_error_compute(sum_squared_obs, sum_obs, sum_squared_error, target.shape[0], squared)


# -------------------------------------------------------------------- LogCosh
def _log_cosh_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    diff = preds - target
    # numerically-stable log(cosh(x)) = x + softplus(-2x) - log 2
    vals = diff + jnp.logaddexp(-2 * diff, 0.0) - jnp.log(2.0)
    return vals.sum(0), preds.shape[0]


def log_cosh_error(preds: Array, target: Array) -> Array:
    """log cosh error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import log_cosh_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = log_cosh_error(preds, target)
        >>> round(float(result), 4)
        0.1685
    """

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[1]
    s, n = _log_cosh_error_update(preds, target, num_outputs)
    return (s / n).squeeze()


# ------------------------------------------------------------------ Minkowski
def _minkowski_distance_update(preds: Array, target: Array, p: float) -> Array:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    if not (isinstance(p, (float, int)) and p >= 1):
        raise ValueError(f"Argument ``p`` expected to be a float larger than 1, but got {p}")
    return (jnp.abs(preds - target) ** p).sum()


def minkowski_distance(preds: Array, target: Array, p: float) -> Array:
    """minkowski distance (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import minkowski_distance
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = minkowski_distance(preds, target, p=3)
        >>> round(float(result), 4)
        1.0772
    """

    s = _minkowski_distance_update(jnp.asarray(preds), jnp.asarray(target), p)
    return s ** (1.0 / p)


# ------------------------------------------------------------------- Tweedie
def _tweedie_deviance_score_update(preds: Array, target: Array, power: float = 0.0) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    if power < 0:
        deviance_score = 2 * (
            jnp.power(jnp.clip(target, min=0), 2 - power) / ((1 - power) * (2 - power))
            - target * jnp.power(preds, 1 - power) / (1 - power)
            + jnp.power(preds, 2 - power) / (2 - power)
        )
    elif power == 0:
        deviance_score = (preds - target) ** 2
    elif 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    elif power == 1:
        deviance_score = 2 * (_safe_xlogy(target, target / preds) + preds - target)
    elif power == 2:
        deviance_score = 2 * (jnp.log(preds / target) + target / preds - 1)
    else:
        deviance_score = 2 * (
            jnp.power(jnp.clip(target, min=0), 2 - power) / ((1 - power) * (2 - power))
            - target * jnp.power(preds, 1 - power) / (1 - power)
            + jnp.power(preds, 2 - power) / (2 - power)
        )
    return deviance_score.sum(), preds.size


def tweedie_deviance_score(preds: Array, target: Array, power: float = 0.0) -> Array:
    """tweedie deviance score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import tweedie_deviance_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = tweedie_deviance_score(preds, target)
        >>> round(float(result), 4)
        0.375
    """

    s, n = _tweedie_deviance_score_update(
        jnp.asarray(preds), jnp.asarray(target), power
    )
    return s / n


# ------------------------------------------------------------------------ CSI
def _critical_success_index_update(
    preds: Array, target: Array, threshold: float, keep_sequence_dim: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    _check_same_shape(preds, target)
    preds, target = _at_least_float32(preds), _at_least_float32(target)
    if keep_sequence_dim is None:
        sum_dims = None
    else:
        sum_dims = tuple(d for d in range(preds.ndim) if d != keep_sequence_dim)
    pred_bin = preds >= threshold
    target_bin = target >= threshold
    hits = (pred_bin & target_bin).sum(sum_dims)
    misses = (~pred_bin & target_bin).sum(sum_dims)
    false_alarms = (pred_bin & ~target_bin).sum(sum_dims)
    return hits, misses, false_alarms


def critical_success_index(
    preds: Array, target: Array, threshold: float, keep_sequence_dim: Optional[int] = None
) -> Array:
    """critical success index (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import critical_success_index
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = critical_success_index(preds, target, threshold=0.5)
        >>> round(float(result), 4)
        1.0
    """

    hits, misses, false_alarms = _critical_success_index_update(
        jnp.asarray(preds), jnp.asarray(target), threshold, keep_sequence_dim
    )
    return _safe_divide(hits, hits + misses + false_alarms)
