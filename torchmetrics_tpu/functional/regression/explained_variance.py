"""Explained variance (reference functional/regression/explained_variance.py)."""
from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape

ALLOWED_MULTIOUTPUT = ("raw_values", "uniform_average", "variance_weighted")


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    num_obs = preds.shape[0]
    sum_error = (target - preds).sum(0)
    diff = target - preds
    sum_squared_error = (diff * diff).sum(0)
    sum_target = target.sum(0)
    sum_squared_target = (target * target).sum(0)
    return num_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    num_obs: Union[int, Array],
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    diff_avg = sum_error / num_obs
    numerator = sum_squared_error / num_obs - diff_avg * diff_avg
    target_avg = sum_target / num_obs
    denominator = sum_squared_target / num_obs - target_avg * target_avg
    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    output_scores = jnp.ones_like(diff_avg)
    valid = nonzero_numerator & nonzero_denominator
    output_scores = jnp.where(
        valid, 1.0 - numerator / jnp.where(valid, denominator, 1.0), output_scores
    )
    output_scores = jnp.where(nonzero_numerator & ~nonzero_denominator, 0.0, output_scores)
    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return output_scores.mean()
    if multioutput == "variance_weighted":
        denom_sum = denominator.sum()
        return (denominator / denom_sum * output_scores).sum()
    raise ValueError(f"Argument `multioutput` must be one of {ALLOWED_MULTIOUTPUT}, but got {multioutput}")


def explained_variance(preds: Array, target: Array, multioutput: str = "uniform_average") -> Array:
    """explained variance (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import explained_variance
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = explained_variance(preds, target)
        >>> round(float(result), 4)
        0.9572
    """

    if multioutput not in ALLOWED_MULTIOUTPUT:
        raise ValueError(f"Argument `multioutput` must be one of {ALLOWED_MULTIOUTPUT}, but got {multioutput}")
    num_obs, sum_error, ss_error, sum_target, ss_target = _explained_variance_update(
        jnp.asarray(preds), jnp.asarray(target)
    )
    return _explained_variance_compute(num_obs, sum_error, ss_error, sum_target, ss_target, multioutput)
