"""Rank-based correlations: Spearman, Kendall, Concordance (reference
functional/regression/{spearman,kendall,concordance}.py).

Spearman = Pearson on ranks (tie-aware average ranks). Kendall tau via O(n²)
pairwise comparisons — a single fused kernel on TPU for the typical n used with
these metrics (the reference's O(n log n) mergesort path is host-sequential and
slower on accelerators until n is very large). Concordance = Lin's CCC from the
same moment states as Pearson.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.pearson import (
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from torchmetrics_tpu.utils.checks import _check_same_shape


def _rank_data_average(x: Array) -> Array:
    """Tie-aware average ranks (scipy rankdata 'average'), 1-indexed.

    O(n log n): sort once, then two searchsorted passes give per-element
    (#less, #less-or-equal); avg rank = (#less + 1 + #lessequal) / 2.
    """
    sorted_x = jnp.sort(x)
    lo = jnp.searchsorted(sorted_x, x, side="left")
    hi = jnp.searchsorted(sorted_x, x, side="right")
    return (lo + 1 + hi) / 2.0


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1.17e-06) -> Array:
    if preds.ndim == 1:
        r_preds = _rank_data_average(preds)
        r_target = _rank_data_average(target)
    else:
        r_preds = jnp.stack([_rank_data_average(preds[:, i]) for i in range(preds.shape[1])], axis=1)
        r_target = jnp.stack([_rank_data_average(target[:, i]) for i in range(target.shape[1])], axis=1)
    preds_diff = r_preds - r_preds.mean(0)
    target_diff = r_target - r_target.mean(0)
    cov = (preds_diff * target_diff).mean(0)
    preds_std = jnp.sqrt((preds_diff * preds_diff).mean(0))
    target_std = jnp.sqrt((target_diff * target_diff).mean(0))
    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0).squeeze()


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """spearman corrcoef (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import spearman_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = spearman_corrcoef(preds, target)
        >>> round(float(result), 4)
        1.0
    """

    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    return _spearman_corrcoef_compute(preds, target)


def _kendall_tau_update(preds: Array, target: Array, variant: str = "b") -> Array:
    """Tau via pairwise concordance counts (one (n, n) compare kernel)."""
    dx = preds[None, :] - preds[:, None]
    dy = target[None, :] - target[:, None]
    sign_prod = jnp.sign(dx) * jnp.sign(dy)
    iu = jnp.triu_indices(preds.shape[0], k=1)
    sp = sign_prod[iu]
    concordant = (sp > 0).sum()
    discordant = (sp < 0).sum()
    n = preds.shape[0]
    n0 = n * (n - 1) / 2
    ties_x = ((dx[iu] == 0)).sum()
    ties_y = ((dy[iu] == 0)).sum()
    ties_xy = ((dx[iu] == 0) & (dy[iu] == 0)).sum()
    if variant == "a":
        return (concordant - discordant) / n0
    if variant == "b":
        return (concordant - discordant) / jnp.sqrt((n0 - ties_x) * (n0 - ties_y))
    # variant c: 2(C−D) / (n²·(m−1)/m), m = min(#distinct x, #distinct y);
    # distinct counts via sorted-diff so the whole thing stays jit-safe
    mx = (jnp.diff(jnp.sort(preds)) != 0).sum() + 1
    my = (jnp.diff(jnp.sort(target)) != 0).sum() + 1
    m = jnp.minimum(mx, my)
    return 2 * (concordant - discordant) / (n**2 * (m - 1) / m)


def kendall_rank_corrcoef(
    preds: Array,
    target: Array,
    variant: str = "b",
    t_test: bool = False,
    alternative: Optional[str] = "two-sided",
) -> Array:
    """Kendall rank correlation (reference kendall.py). ``t_test`` returns (tau, p).

    Example:
        >>> from torchmetrics_tpu.functional import kendall_rank_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = kendall_rank_corrcoef(preds, target)
        >>> round(float(result), 4)
        1.0
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    _check_same_shape(preds, target)
    if variant not in ("a", "b", "c"):
        raise ValueError(f"Argument `variant` is expected to be one of 'a', 'b', 'c' but got {variant}")
    if preds.ndim == 1:
        tau = _kendall_tau_update(preds, target, variant)
    else:
        tau = jnp.stack([_kendall_tau_update(preds[:, i], target[:, i], variant) for i in range(preds.shape[1])])
    if not t_test:
        return tau.squeeze()
    # normal-approximation p-value (reference kendall.py _calculate_p_value)
    n = preds.shape[0]
    se = jnp.sqrt(2 * (2 * n + 5) / (9 * n * (n - 1)))
    import jax.scipy.stats as jstats

    z = tau / se
    if alternative == "two-sided":
        p = 2 * (1 - jstats.norm.cdf(jnp.abs(z)))
    elif alternative == "greater":
        p = 1 - jstats.norm.cdf(z)
    else:
        p = jstats.norm.cdf(z)
    return tau.squeeze(), p.squeeze()


def _concordance_corrcoef_compute(
    mean_x: Array, mean_y: Array, var_x: Array, var_y: Array, corr_xy: Array, nb: Array
) -> Array:
    """Lin's CCC from moment states (reference concordance.py:22-34)."""
    pearson = _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    return (2.0 * pearson * jnp.sqrt(var_x) * jnp.sqrt(var_y)) / (var_x + var_y + (mean_x - mean_y) ** 2)


def concordance_corrcoef(preds: Array, target: Array) -> Array:
    """concordance corrcoef (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import concordance_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = concordance_corrcoef(preds, target)
        >>> round(float(result[0]), 4)  # shape (1,), like the reference
        0.9777
    """

    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    d = preds.shape[1] if preds.ndim == 2 else 1
    _temp = jnp.zeros(d)
    mean_x, mean_y, var_x = _temp, _temp, _temp
    var_y, corr_xy, nb = _temp, _temp, _temp
    mean_x, mean_y, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, mean_x, mean_y, var_x, var_y, corr_xy, nb, num_outputs=d
    )
    # NB unlike pearson, the reference does NOT squeeze here — 1-D input
    # yields shape (1,) (reference concordance.py doctest: tensor([0.9777]))
    return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, nb)
