"""Pearson correlation (reference functional/regression/pearson.py + regression/pearson.py:28-70).

Streaming mean/var/cov states with the Chan et al. pairwise merge — the template
for all parallel moment-merging in this framework (also used by the `merge`
protocol for distributed reduction of per-device moment states).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    num_prior: Array,
    num_outputs: int,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Streaming update of first/second moments (reference pearson.py:22-77)."""
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    num_obs = preds.shape[0]
    # weighted running mean; with num_prior == 0 this reduces to the batch mean,
    # so no branch is needed (and the batch-size-1 case stays correct)
    mx_new = (num_prior * mean_x + preds.sum(0)) / (num_prior + num_obs)
    my_new = (num_prior * mean_y + target.sum(0)) / (num_prior + num_obs)
    num_prior = num_prior + num_obs
    var_x = var_x + ((preds - mx_new) * (preds - mean_x)).sum(0)
    var_y = var_y + ((target - my_new) * (target - mean_y)).sum(0)
    corr_xy = corr_xy + ((preds - mx_new) * (target - mean_y)).sum(0)
    return mx_new, my_new, var_x, var_y, corr_xy, num_prior


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Chan et al. pairwise merge of per-device moment states (reference pearson.py:28-70).

    Inputs are stacked per-device values with leading axis = world size.
    """
    if means_x.ndim == 0:
        return means_x, means_y, vars_x, vars_y, corrs_xy, nbs
    if means_x.shape[0] == 1:
        return means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        # standard Chan et al. pairwise merge: the cross term n1*n2/nb·Δm² folds
        # the between-shard mean shift into the pooled second moments
        factor = jnp.where(nb == 0, 0.0, n1 * n2 / jnp.where(nb == 0, 1.0, nb))
        dx = mx2 - mx1
        dy = my2 - my1
        mean_x = jnp.where(nb == 0, 0.0, (n1 * mx1 + n2 * mx2) / jnp.where(nb == 0, 1.0, nb))
        mean_y = jnp.where(nb == 0, 0.0, (n1 * my1 + n2 * my2) / jnp.where(nb == 0, 1.0, nb))
        var_x = vx1 + vx2 + factor * dx * dx
        var_y = vy1 + vy2 + factor * dy * dy
        corr_xy = cxy1 + cxy2 + factor * dx * dy
        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return mx1, my1, vx1, vy1, cxy1, n1


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    """Correlation from accumulated second moments (reference pearson.py:80-114)."""
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    # reference pearson.py:104-111: near-zero variance makes the estimate
    # numerically meaningless (the reference returns clamped float noise, we
    # return NaN for the exactly-zero case) — both sides warn about it. The
    # warning is host-side only; skip it under jit where values are traced.
    try:
        bound = float(np.sqrt(np.finfo(np.float32).eps))
        if bool((var_x < bound).any() | (var_y < bound).any()):
            rank_zero_warn(
                "The variance of predictions or target is close to zero. This can cause instability in Pearson"
                " correlation coefficient, leading to wrong results.",
                UserWarning,
            )
    except jax.errors.TracerBoolConversionError:
        pass
    denom = jnp.sqrt(var_x * var_y)
    corrcoef = jnp.where(denom == 0, jnp.nan, corr_xy / jnp.where(denom == 0, 1.0, denom))
    return jnp.clip(corrcoef, -1.0, 1.0).squeeze()


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Compute Pearson correlation coefficient (reference pearson.py:106).

    Example:
        >>> from torchmetrics_tpu.functional import pearson_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = pearson_corrcoef(preds, target)
        >>> round(float(result), 4)
        0.9849
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    d = preds.shape[1] if preds.ndim == 2 else 1
    _temp = jnp.zeros(d)
    mean_x, mean_y, var_x = _temp, _temp, _temp
    var_y, corr_xy, nb = _temp, _temp, _temp
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, mean_x, mean_y, var_x, var_y, corr_xy, nb, num_outputs=d
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
