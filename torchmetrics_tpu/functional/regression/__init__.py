from torchmetrics_tpu.functional.regression.basic import (  # noqa: F401
    critical_success_index,
    log_cosh_error,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    minkowski_distance,
    relative_squared_error,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
    weighted_mean_absolute_percentage_error,
)
from torchmetrics_tpu.functional.regression.explained_variance import explained_variance  # noqa: F401
from torchmetrics_tpu.functional.regression.misc import cosine_similarity, kl_divergence  # noqa: F401
from torchmetrics_tpu.functional.regression.pearson import pearson_corrcoef  # noqa: F401
from torchmetrics_tpu.functional.regression.r2 import r2_score  # noqa: F401
from torchmetrics_tpu.functional.regression.rank_based import (  # noqa: F401
    concordance_corrcoef,
    kendall_rank_corrcoef,
    spearman_corrcoef,
)
