"""R² score (reference functional/regression/r2.py)."""
from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _r2_score_update(preds: Array, target: Array) -> Tuple[Array, Array, Array, int]:
    _check_same_shape(preds, target)
    if preds.ndim > 2:
        raise ValueError(
            f"Expected both prediction and target to be 1D or 2D tensors, but received tensors with dimension {preds.shape}"
        )
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    sum_obs = target.sum(0)
    sum_squared_obs = (target * target).sum(0)
    residual = ((target - preds) ** 2).sum(0)
    return sum_squared_obs, sum_obs, residual, target.shape[0]


def _r2_score_compute(
    sum_squared_obs: Array,
    sum_obs: Array,
    residual: Array,
    num_obs: Union[int, Array],
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    """R² from sufficient statistics (reference r2.py:47-105)."""
    if isinstance(num_obs, int) and num_obs < 2:
        raise ValueError("Needs at least two samples to calculate r2 score.")
    mean_obs = sum_obs / num_obs
    tss = sum_squared_obs - sum_obs * mean_obs
    # near-constant handling (reference r2.py:82-91): rss≈0 → perfect fit
    # scores 1 even if tss is also ~0; rss nonzero against a ~constant
    # target scores 0 (both at the reference's atol=1e-4 isclose)
    cond_rss = ~jnp.isclose(residual, 0.0, atol=1e-4)
    cond_tss = ~jnp.isclose(tss, 0.0, atol=1e-4)
    raw_scores = jnp.where(
        cond_rss & cond_tss,
        1 - (residual / jnp.where(cond_tss, tss, 1.0)),
        jnp.where(cond_rss & ~cond_tss, 0.0, 1.0),
    )
    if multioutput == "raw_values":
        r2 = raw_scores
    elif multioutput == "uniform_average":
        r2 = jnp.mean(raw_scores)
    elif multioutput == "variance_weighted":
        tss_sum = tss.sum()
        r2 = (tss / tss_sum * raw_scores).sum()
    else:
        raise ValueError(
            "Argument `multioutput` must be either `raw_values`,"
            f" `uniform_average` or `variance_weighted`. Received {multioutput}."
        )
    if adjusted < 0 or not isinstance(adjusted, int):
        raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
    if adjusted != 0:
        if isinstance(num_obs, int) and adjusted > num_obs - 1:
            rank_zero_warn(
                "More independent regressions than data points in adjusted r2 score. Falls back to standard r2 score.",
                UserWarning,
            )
            return r2
        if isinstance(num_obs, int) and adjusted == num_obs - 1:
            rank_zero_warn("Division by zero in adjusted r2 score. Falls back to standard r2 score.", UserWarning)
            return r2
        return 1 - (1 - r2) * (num_obs - 1) / (num_obs - adjusted - 1)
    return r2


def r2_score(
    preds: Array,
    target: Array,
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    """r2 score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import r2_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> result = r2_score(preds, target)
        >>> round(float(result), 4)
        0.9486
    """

    sum_squared_obs, sum_obs, residual, num_obs = _r2_score_update(jnp.asarray(preds), jnp.asarray(target))
    return _r2_score_compute(sum_squared_obs, sum_obs, residual, num_obs, adjusted, multioutput)
