"""BLEU and SacreBLEU.

Reference: functional/text/bleu.py (clipped n-gram precision + brevity penalty,
corpus-level counter states) and functional/text/sacre_bleu.py (same update with
the sacrebleu tokenizer family; tokenizers re-implemented here from the
sacrebleu spec: none/13a/intl/char/zh; ja/ko-mecab and flores require external
tokenizer wheels and are gated).

TPU design: n-gram counting is host work (hash maps over tuples of words — no
tensor representation beats a Counter here, and the reference agrees); the
states (`numerator`, `denominator`, `preds_len`, `target_len`) are dense jnp
vectors of shape (n_gram,), psum-synced across the mesh, and the compute stage
is pure jnp (log/exp/brevity penalty) so it can run under jit.
"""
from __future__ import annotations

import re
import unicodedata
from collections import Counter
from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.helper import _count_ngrams


def _tokenize_fn(sentence: str) -> Sequence[str]:
    """Default whitespace tokenizer (reference bleu.py:47-57)."""
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    numerator: Array,
    denominator: Array,
    preds_len: Array,
    target_len: Array,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Accumulate clipped n-gram matches (reference bleu.py:60-105).

    Returns updated (preds_len, target_len, numerator, denominator) — unlike the
    reference we cannot mutate tensors in place, so all four come back.
    """
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok = [tokenizer(line) if line else [] for line in preds]
    num = [0] * n_gram
    den = [0] * n_gram
    p_len = 0
    t_len = 0
    for pred, targets in zip(preds_tok, target_tok):
        p_len += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        t_len += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter = _count_ngrams(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngrams(tgt, n_gram)
        clipped = preds_counter & target_counter
        for ngram, cnt in clipped.items():
            num[len(ngram) - 1] += cnt
        for ngram, cnt in preds_counter.items():
            den[len(ngram) - 1] += cnt
    return (
        preds_len + p_len,
        target_len + t_len,
        numerator + jnp.asarray(num, dtype=numerator.dtype),
        denominator + jnp.asarray(den, dtype=denominator.dtype),
    )


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    """Geometric mean of clipped precisions × brevity penalty (bleu.py:108-146).

    Pure jnp, branch-free where the value depends on data (jit-safe): the
    zero-match early-out and BP condition become `jnp.where`.
    """
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(
            jnp.where(denominator[0] > 0, numerator[0] / jnp.maximum(denominator[0], 1), 0.0)
        )
    else:
        precision_scores = numerator / jnp.maximum(denominator, 1)
    log_precision = jnp.asarray(weights) * jnp.log(jnp.maximum(precision_scores, 1e-30))
    geometric_mean = jnp.exp(jnp.sum(log_precision))
    brevity_penalty = jnp.where(
        preds_len > target_len, 1.0, jnp.exp(1 - (target_len / jnp.maximum(preds_len, 1e-9)))
    )
    return jnp.where(jnp.min(numerator) == 0.0, 0.0, brevity_penalty * geometric_mean)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """Corpus BLEU of machine-translated text (reference bleu.py:149-209).

    Example:
        >>> from torchmetrics_tpu.functional import bleu_score
        >>> import jax.numpy as jnp
        >>> preds = ["the cat sat on the mat"]
        >>> target = [["a cat sat on the mat"]]
        >>> result = bleu_score(preds, target)
        >>> round(float(result), 4)
        0.7598
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    preds_len = jnp.asarray(0.0)
    target_len = jnp.asarray(0.0)
    preds_len, target_len, numerator, denominator = _bleu_score_update(
        preds_, target_, numerator, denominator, preds_len, target_len, n_gram, _tokenize_fn
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)


# ----------------------------------------------------------------- SacreBLEU
AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")

# CJK codepoint ranges the `zh` tokenizer splits on (sacrebleu tokenizer_zh spec)
_UCODE_RANGES = (
    ("㐀", "䶵"), ("一", "龥"), ("龦", "龻"),
    ("豈", "鶴"), ("侮", "頻"), ("並", "龎"),
    # NB kept as the reference writes them (reference sacre_bleu.py:70-71):
    # "\\u20000" parses as the TWO-char string "\\u2000"+"0", so the
    # lexicographic range check treats the whole U+2000..U+2A6D band (e.g.
    # '\u20ac') as Chinese - a reference quirk reproduced for parity
    ("\u20000", "\u2a6d6"), ("\u2f800", "\u2fa1d"),
    ("＀", "￯"), ("⺀", "⻿"), ("　", "〿"),
    ("㇀", "㇯"), ("⼀", "⿟"), ("⿰", "⿿"),
    ("㄀", "ㄯ"), ("ㆠ", "ㆿ"), ("︐", "︟"),
    ("︰", "﹏"), ("☀", "⛿"), ("✀", "➿"),
    ("㈀", "㋿"), ("㌀", "㏿"),
)

_13A_REGEX = (
    (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
    (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
    (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
    (re.compile(r"([0-9])(-)"), r"\1 \2 "),
)


class _SacreBLEUTokenizer:
    """The sacrebleu tokenizer family (reference sacre_bleu.py:98-455).

    The `intl` tokenizer is implemented with unicodedata category checks
    (`P*`/`S*`/`N*`) instead of the `regex` wheel's \\p classes.
    """

    def __init__(self, tokenize: str, lowercase: bool = False) -> None:
        self._check_tokenizers_validity(tokenize)
        self.tokenize_fn = getattr(self, "_tokenize_" + {"none": "base", "13a": "13a", "zh": "zh", "intl": "international", "char": "char"}[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        return self._lower(self.tokenize_fn(line), self.lowercase).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        cls._check_tokenizers_validity(tokenize)
        fn = getattr(cls, "_tokenize_" + {"none": "base", "13a": "13a", "zh": "zh", "intl": "international", "char": "char"}[tokenize])
        return cls._lower(fn(line), lowercase).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in _13A_REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "").replace("-\n", "").replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"').replace("&amp;", "&").replace("&lt;", "<").replace("&gt;", ">")
        return cls._tokenize_regex(f" {line} ")

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        parts = []
        for ch in line:
            if cls._is_chinese_char(ch):
                parts.append(f" {ch} ")
            else:
                parts.append(ch)
        return cls._tokenize_regex("".join(parts))

    @staticmethod
    def _sub_pairs(line: str, rule: str) -> str:
        """One non-overlapping left-to-right pass of the reference's intl
        regex rules (reference sacre_bleu.py:122-129), expressed with
        unicodedata category checks instead of the `regex` wheel's \\p
        classes. ``rule``: "nonnum_punct" = (\\P{N})(\\p{P}) -> "\\1 \\2 ",
        "punct_nonnum" = (\\p{P})(\\P{N}) -> " \\1 \\2", "symbol" =
        (\\p{S}) -> " \\1 "."""
        cat = unicodedata.category
        out: List[str] = []
        i = 0
        n = len(line)
        while i < n:
            ch = line[i]
            if rule == "symbol":
                if cat(ch).startswith("S"):
                    out.append(f" {ch} ")
                else:
                    out.append(ch)
                i += 1
                continue
            if i + 1 < n:
                nxt = line[i + 1]
                if rule == "nonnum_punct" and not cat(ch).startswith("N") and cat(nxt).startswith("P"):
                    out.append(f"{ch} {nxt} ")
                    i += 2
                    continue
                if rule == "punct_nonnum" and cat(ch).startswith("P") and not cat(nxt).startswith("N"):
                    out.append(f" {ch} {nxt}")
                    i += 2
                    continue
            out.append(ch)
            i += 1
        return "".join(out)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        # three cascaded passes, exactly the reference's rule order — spaces
        # inserted by earlier passes participate in later ones (space is
        # \P{N}), which a single char loop cannot reproduce
        line = cls._sub_pairs(line, "nonnum_punct")
        line = cls._sub_pairs(line, "punct_nonnum")
        line = cls._sub_pairs(line, "symbol")
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(ch for ch in line)

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line

    @classmethod
    def _check_tokenizers_validity(cls, tokenize: str) -> None:
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(
                f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}."
                " (`ja-mecab`/`ko-mecab`/`flores*` require external tokenizer wheels not bundled here.)"
            )


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """SacreBLEU: BLEU with the standardized tokenizers (sacre_bleu.py:458-532).

    Example:
        >>> from torchmetrics_tpu.functional import sacre_bleu_score
        >>> import jax.numpy as jnp
        >>> preds = ["the cat sat on the mat"]
        >>> target = [["a cat sat on the mat"]]
        >>> result = sacre_bleu_score(preds, target)
        >>> round(float(result), 4)
        0.7598
    """
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    preds_len = jnp.asarray(0.0)
    target_len = jnp.asarray(0.0)
    tokenize_fn = partial(_SacreBLEUTokenizer.tokenize, tokenize=tokenize, lowercase=lowercase)
    preds_len, target_len, numerator, denominator = _bleu_score_update(
        preds, [[t] if isinstance(t, str) else t for t in target],
        numerator, denominator, preds_len, target_len, n_gram, tokenize_fn,
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
