"""CHRF / chrF++ score.

Reference: functional/text/chrf.py (649 LoC). Popović 2015/2017: F-beta over
character n-grams (orders 1..n_char_order) plus optional word n-grams
(chrF++, orders 1..n_word_order), averaged over all orders, ×100.

TPU redesign of the state layout: the reference keeps 6 dicts of per-order
scalar tensors (chrf.py:49-79); here each becomes a single dense jnp vector of
shape ``(order,)`` — one `psum` per state syncs the whole family across the
mesh, and the compute stage is vectorized jnp over the order axis.
"""
from __future__ import annotations

import string
from collections import Counter
from itertools import chain
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.helper import _ngram_counts_by_order

_EPS_SMOOTHING = 1e-16
_PUNCTUATIONS = set(string.punctuation)


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    """Character stream, optionally stripping spaces (reference chrf.py:82-95)."""
    if whitespace:
        return list(sentence)
    # NB only ASCII spaces are removed (after a strip): unicode whitespace
    # like U+3000 stays a character, exactly as the reference does
    return list(sentence.strip().replace(" ", ""))


def _separate_word_and_punctuation(word: str) -> List[str]:
    """Split leading/trailing punctuation off a word (reference chrf.py:98-118)."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    """Word stream with separated punctuation (reference chrf.py:121-131)."""
    return list(chain.from_iterable(_separate_word_and_punctuation(word) for word in sentence.strip().split()))


def _sentence_counts(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[Dict[int, Counter], Dict[int, Counter]]:
    if lowercase:
        sentence = sentence.lower()
    char_counts = _ngram_counts_by_order(_get_characters(sentence, whitespace), n_char_order)
    word_counts = _ngram_counts_by_order(_get_words_and_punctuation(sentence), n_word_order)
    return char_counts, word_counts


def _totals(counts: Dict[int, Counter], order: int) -> jnp.ndarray:
    return jnp.asarray([sum(counts[n].values()) for n in range(1, order + 1)], dtype=jnp.float32)


def _matches(hyp: Dict[int, Counter], ref: Dict[int, Counter], order: int) -> jnp.ndarray:
    """Clipped per-order matches (reference chrf.py:203-223)."""
    out = []
    for n in range(1, order + 1):
        h, r = hyp[n], ref[n]
        out.append(sum(min(cnt, r[g]) for g, cnt in h.items()))
    return jnp.asarray(out, dtype=jnp.float32)


def _chrf_fscore_vec(matching: Array, hyp_total: Array, ref_total: Array, beta: float) -> Array:
    """Per-order F-beta vector (reference chrf.py:242-296), pure jnp."""
    precision = jnp.where(hyp_total > 0, matching / jnp.maximum(hyp_total, 1), 0.0)
    recall = jnp.where(ref_total > 0, matching / jnp.maximum(ref_total, 1), 0.0)
    denom = jnp.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    return (1 + beta**2) * precision * recall / denom


def _chrf_score_compute(
    total_preds_char: Array, total_preds_word: Array,
    total_target_char: Array, total_target_word: Array,
    total_matching_char: Array, total_matching_word: Array,
    n_order: float, beta: float,
) -> Array:
    """Average F-beta over all char+word orders (reference chrf.py:439-474; 0-1 scale)."""
    char_f = _chrf_fscore_vec(total_matching_char, total_preds_char, total_target_char, beta)
    word_f = _chrf_fscore_vec(total_matching_word, total_preds_word, total_target_word, beta)
    return (jnp.sum(char_f) + jnp.sum(word_f)) / n_order


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    total_preds_char: Array, total_preds_word: Array,
    total_target_char: Array, total_target_word: Array,
    total_matching_char: Array, total_matching_word: Array,
    n_char_order: int, n_word_order: int, n_order: float,
    beta: float, lowercase: bool, whitespace: bool,
    sentence_chrf_score: Optional[List[Array]] = None,
) -> Tuple[Array, Array, Array, Array, Array, Array, Optional[List[Array]]]:
    """Accumulate corpus statistics; best reference per sentence (chrf.py:385-436)."""
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds_l) != len(target_l):
        raise ValueError(f"Corpus has different size {len(preds_l)} != {len(target_l)}")

    for pred, refs in zip(preds_l, target_l):
        hyp_char, hyp_word = _sentence_counts(pred, n_char_order, n_word_order, lowercase, whitespace)
        hyp_char_total = _totals(hyp_char, n_char_order)
        hyp_word_total = _totals(hyp_word, n_word_order)

        best_f = None
        best = None
        for ref in refs:
            ref_char, ref_word = _sentence_counts(ref, n_char_order, n_word_order, lowercase, whitespace)
            ref_char_total = _totals(ref_char, n_char_order)
            ref_word_total = _totals(ref_word, n_word_order)
            match_char = _matches(hyp_char, ref_char, n_char_order)
            match_word = _matches(hyp_word, ref_word, n_word_order)
            f = float(
                _chrf_score_compute(
                    hyp_char_total, hyp_word_total, ref_char_total, ref_word_total,
                    match_char, match_word, n_order, beta,
                )
            )
            if best_f is None or f > best_f:
                best_f = f
                best = (ref_char_total, ref_word_total, match_char, match_word)

        assert best is not None
        ref_char_total, ref_word_total, match_char, match_word = best
        total_preds_char = total_preds_char + hyp_char_total
        total_preds_word = total_preds_word + hyp_word_total
        total_target_char = total_target_char + ref_char_total
        total_target_word = total_target_word + ref_word_total
        total_matching_char = total_matching_char + match_char
        total_matching_word = total_matching_word + match_word
        if sentence_chrf_score is not None:
            sentence_chrf_score.append(jnp.asarray(best_f))

    return (
        total_preds_char, total_preds_word, total_target_char, total_target_word,
        total_matching_char, total_matching_word, sentence_chrf_score,
    )


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """chrF/chrF++ score (reference chrf.py:477-649).

    Example:
        >>> from torchmetrics_tpu.functional import chrf_score
        >>> import jax.numpy as jnp
        >>> preds = ["the cat sat on the mat"]
        >>> target = [["a cat sat on the mat"]]
        >>> result = chrf_score(preds, target)
        >>> round(float(result), 4)
        0.8713
    """
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")
    n_order = float(n_char_order + n_word_order)

    tp_char = jnp.zeros(n_char_order)
    tp_word = jnp.zeros(n_word_order)
    tt_char = jnp.zeros(n_char_order)
    tt_word = jnp.zeros(n_word_order)
    tm_char = jnp.zeros(n_char_order)
    tm_word = jnp.zeros(n_word_order)
    sentence_scores: Optional[List[Array]] = [] if return_sentence_level_score else None

    tp_char, tp_word, tt_char, tt_word, tm_char, tm_word, sentence_scores = _chrf_score_update(
        preds, target, tp_char, tp_word, tt_char, tt_word, tm_char, tm_word,
        n_char_order, n_word_order, n_order, beta, lowercase, whitespace, sentence_scores,
    )
    corpus = _chrf_score_compute(tp_char, tp_word, tt_char, tt_word, tm_char, tm_word, n_order, beta)
    if return_sentence_level_score and sentence_scores is not None:
        return corpus, jnp.stack(sentence_scores) if sentence_scores else jnp.zeros(0)
    return corpus
