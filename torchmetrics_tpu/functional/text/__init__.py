"""Functional text metrics (reference: functional/text/__init__.py)."""
from torchmetrics_tpu.functional.text.asr import (  # noqa: F401
    char_error_rate,
    match_error_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from torchmetrics_tpu.functional.text.bert import bert_score  # noqa: F401
from torchmetrics_tpu.functional.text.bleu import bleu_score, sacre_bleu_score  # noqa: F401
from torchmetrics_tpu.functional.text.chrf import chrf_score  # noqa: F401
from torchmetrics_tpu.functional.text.edit import edit_distance, extended_edit_distance  # noqa: F401
from torchmetrics_tpu.functional.text.infolm import infolm  # noqa: F401
from torchmetrics_tpu.functional.text.perplexity import perplexity  # noqa: F401
from torchmetrics_tpu.functional.text.rouge import rouge_score  # noqa: F401
from torchmetrics_tpu.functional.text.squad import squad  # noqa: F401
from torchmetrics_tpu.functional.text.ter import translation_edit_rate  # noqa: F401

__all__ = [
    "bert_score",
    "bleu_score",
    "char_error_rate",
    "chrf_score",
    "edit_distance",
    "extended_edit_distance",
    "infolm",
    "match_error_rate",
    "perplexity",
    "rouge_score",
    "sacre_bleu_score",
    "squad",
    "translation_edit_rate",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",
]
