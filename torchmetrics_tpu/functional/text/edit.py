"""Levenshtein EditDistance + ExtendedEditDistance (EED).

Reference: functional/text/edit.py (plain char-level Levenshtein with
substitution_cost and batch reduction) and functional/text/eed.py (EED — the
CDER-grid DP with long jumps, Stanchev/Wang/Ney WMT'19; re-implemented here
from the algorithm description, not the RWTH code).
"""
from __future__ import annotations

import re
import unicodedata
from math import inf
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.helper import _batch_distances


# --------------------------------------------------------------- EditDistance
def _edit_distance_update(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    substitution_cost: int = 1,
) -> Array:
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [target] if isinstance(target, str) else list(target)
    if not all(isinstance(x, str) for x in preds_l):
        raise ValueError(f"Expected all values in argument `preds` to be string type, but got {preds_l}")
    if not all(isinstance(x, str) for x in target_l):
        raise ValueError(f"Expected all values in argument `target` to be string type, but got {target_l}")
    if len(preds_l) != len(target_l):
        raise ValueError(
            f"Expected argument `preds` and `target` to have same length, but got {len(preds_l)} and {len(target_l)}"
        )
    if substitution_cost == 1:
        _, distances = _batch_distances(preds_l, target_l, char_level=True)
    else:
        from torchmetrics_tpu.native import batch_edit_distance

        distances = batch_edit_distance([(list(p), list(t)) for p, t in zip(preds_l, target_l)], substitution_cost)
    return jnp.asarray(distances, dtype=jnp.int32)


def _edit_distance_compute(
    edit_scores: Array,
    num_elements: Union[Array, int],
    reduction: Optional[str] = "mean",
) -> Array:
    if edit_scores.size == 0:
        return jnp.asarray(0, dtype=jnp.int32)
    if reduction == "mean":
        return edit_scores.sum() / num_elements
    if reduction == "sum":
        return edit_scores.sum()
    if reduction is None or reduction == "none":
        return edit_scores
    raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")


def edit_distance(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    substitution_cost: int = 1,
    reduction: Optional[str] = "mean",
) -> Array:
    """Char-level Levenshtein distance over a batch (reference edit.py:65-119).

    Example:
        >>> from torchmetrics_tpu.functional import edit_distance
        >>> preds = ["kitten"]
        >>> target = ["sitting"]
        >>> result = edit_distance(preds, target)
        >>> round(float(result), 4)
        3.0
    """
    distance = _edit_distance_update(preds, target, substitution_cost)
    return _edit_distance_compute(distance, num_elements=distance.size, reduction=reduction)


# ------------------------------------------------------------------------ EED
def _eed_dp(hyp: str, ref: str, alpha: float, rho: float, deletion: float, insertion: float) -> float:
    """One-sentence EED via the CDER alignment grid with long jumps.

    Columns index hypothesis characters; rows sweep reference characters. At
    each reference space a "jump" edge (cost ``alpha``) lets the alignment
    restart from the best column, and per-column visit counts accumulate the
    rho-weighted coverage penalty (reference eed.py:116-171).
    """
    n = len(hyp)
    visits = [-1] * (n + 1)
    row = [1.0] * (n + 1)
    row[0] = 0.0
    for w in range(1, len(ref) + 1):
        ref_ch = ref[w - 1]
        next_row = [inf] * (n + 1)
        next_row[0] = row[0] + 1.0
        for i in range(1, n + 1):
            next_row[i] = min(
                next_row[i - 1] + deletion,
                row[i - 1] + (0.0 if hyp[i - 1] == ref_ch else 1.0),
                row[i] + insertion,
            )
        min_index = next_row.index(min(next_row))
        visits[min_index] += 1
        if ref_ch == " ":
            jump = alpha + next_row[min_index]
            next_row = [min(x, jump) for x in next_row]
        row = next_row
    coverage = rho * sum(x if x >= 0 else 1 for x in visits)
    return min(1.0, (row[-1] + coverage) / (float(len(ref)) + coverage))


_EED_EN_INTERPUNCTION = [(".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")]
_EED_EN_RE = [
    (r"\s+", r" "),
    (r"(\d) ([.,]) (\d)", r"\1\2\3"),
    # NB: the trailing " ." is space + any-char, faithfully matching the
    # reference's (unescaped) pattern so scores stay bit-identical
    (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),
]
_EED_EN_ABBREV = [("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")]


def _eed_preprocess_en(sentence: str) -> str:
    """English normalisation: spaced interpunction + abbreviation repair (eed.py:174-216).

    Returns the sentence wrapped in single spaces (the DP's jump sentinels),
    exactly as the reference does.
    """
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, replacement in _EED_EN_INTERPUNCTION:
        sentence = sentence.replace(pattern, replacement)
    for pattern, replacement in _EED_EN_RE:
        sentence = re.sub(pattern, replacement, sentence)
    for pattern, replacement in _EED_EN_ABBREV:
        sentence = sentence.replace(pattern, replacement)
    return " " + sentence + " "


def _eed_preprocess_ja(sentence: str) -> str:
    """Japanese normalisation: rstrip + NFKC only (eed.py:219-233) — no sentinels."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[Array]:
    """Sentence-level EED scores: best (lowest) over references (eed.py:290-361)."""
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds_l) != len(target_l):
        raise ValueError(f"Corpus has different size {len(preds_l)} != {len(target_l)}")
    preprocess = _eed_preprocess_en if language == "en" else _eed_preprocess_ja
    if language not in ("en", "ja"):
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    scores: List[Array] = []
    for pred, refs in zip(preds_l, target_l):
        hyp = preprocess(pred)
        best = None
        for ref in refs:
            score = _eed_dp(hyp, preprocess(ref), alpha, rho, deletion, insertion)
            best = score if best is None or score < best else best
        if best is not None:
            scores.append(jnp.asarray(best, dtype=jnp.float32))
    return scores


def _eed_compute(sentence_level_scores: List[Array]) -> Array:
    """Corpus EED = average of sentence scores (eed.py:236-249)."""
    if not sentence_level_scores:
        return jnp.asarray(0.0)
    return jnp.stack(sentence_level_scores).mean()


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """Extended Edit Distance (reference eed.py:364-414).

    Example:
        >>> from torchmetrics_tpu.functional import extended_edit_distance
        >>> import jax.numpy as jnp
        >>> preds = ["the cat sat on the mat"]
        >>> target = [["a cat sat on the mat"]]
        >>> result = extended_edit_distance(preds, target)
        >>> round(float(result), 4)
        0.1452
    """
    scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    corpus = _eed_compute(scores)
    if return_sentence_level_score:
        return corpus, jnp.stack(scores) if scores else jnp.zeros(0)
    return corpus
