"""Perplexity — the fully device-native text metric.

Reference: functional/text/perplexity.py:65-126. TPU design: the gathered-logit
identity ``-log p[t] = logsumexp(logits) - logits[t]`` — one reduction over the
(N, V) logits without materializing a full log-prob array (numerically better
than the reference's softmax→index→log, and HBM-bandwidth-shaped);
`ignore_index` handled by a mask so shapes stay static under jit. The two
outputs are psum-able scalars.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _is_concrete


def _check_shape_and_type_consistency(preds: Array, target: Array) -> None:
    """Shape/type validation (reference perplexity.py:21-63)."""
    if preds.ndim != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {preds.ndim}."
        )
    if target.ndim != 2:
        raise ValueError(
            f"Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len], but got {target.ndim}."
        )
    if preds.shape[:2] != target.shape:
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {preds.shape[:2]} and {target.shape}."
        )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise TypeError(f"Input tensor `preds` is expected to be of floating point type but got {preds.dtype}.")
    if not jnp.issubdtype(target.dtype, jnp.integer):
        raise TypeError(f"Input tensor `target` is expected to be of integer type but got {target.dtype}.")


def _perplexity_update(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    """Σ(-log p[target]) and token count (reference perplexity.py:66-111), jit-safe.

    ``-log p[t] = logsumexp(logits) - logits[t]``: the gathered-logit identity
    reads the (N, V) logits for one reduction and never materializes the full
    (N, V) log-prob array a ``log_softmax`` + gather would write and re-read —
    the HBM-bandwidth-shaped formulation of the same math.
    """
    _check_shape_and_type_consistency(preds, target)
    if _is_concrete(preds) and jax.default_backend() == "cpu":
        # eager CPU fallback: XLA:CPU lowers the vocab logsumexp to scalar
        # libm exp calls (~15 ms for 1024x2000 where vectorized numpy takes
        # ~5 ms); same pattern as the binned-curve off-TPU fallback. Traced
        # calls (tracers) and accelerator backends always take the jnp path.
        return _perplexity_update_host(preds, target, ignore_index)
    logits = preds.reshape(-1, preds.shape[-1]).astype(jnp.float32)
    target_flat = target.reshape(-1)

    if ignore_index is not None:
        mask = target_flat != ignore_index
        target_flat = jnp.where(mask, target_flat, 0)
    else:
        mask = jnp.ones_like(target_flat, dtype=bool)

    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    token_logits = jnp.take_along_axis(logits, target_flat[:, None], axis=1).squeeze(1)
    token_log_probs = token_logits - lse
    total_log_probs = -jnp.sum(token_log_probs * mask)
    count = jnp.sum(mask)
    return total_log_probs, count


def _perplexity_update_host(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    """Vectorized-numpy twin of the jnp update (same math, same state dtypes)."""
    import numpy as np

    logits = np.asarray(preds, dtype=np.float32).reshape(-1, preds.shape[-1])
    target_flat = np.asarray(target).reshape(-1)
    if ignore_index is not None:
        mask = target_flat != ignore_index
        target_flat = np.where(mask, target_flat, 0)
    else:
        mask = np.ones_like(target_flat, dtype=bool)
    m = logits.max(axis=1)
    lse = m + np.log(np.exp(logits - m[:, None]).sum(axis=1))
    # jnp.take_along_axis fills out-of-bounds gathers with NaN (both eager and
    # jit); reproduce that exactly so unmasked out-of-range targets poison the
    # total identically on both paths (numpy would wrap/IndexError instead)
    oob = (target_flat < 0) | (target_flat >= logits.shape[1])
    token_logits = np.take_along_axis(
        logits, np.clip(target_flat, 0, logits.shape[1] - 1)[:, None], axis=1
    ).squeeze(1)
    token_logits = np.where(oob, np.nan, token_logits)
    total = -((token_logits - lse) * mask).sum()
    return jnp.asarray(total, dtype=jnp.float32), jnp.asarray(int(mask.sum()), dtype=jnp.int32)


def _perplexity_compute(total: Array, count: Array) -> Array:
    """exp of the mean negative log-likelihood (reference perplexity.py:114-126)."""
    return jnp.exp(total / count)


def perplexity(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Array:
    """Perplexity of a language model's token predictions (reference perplexity.py:129-143).

    Args:
        preds: logits of shape [batch_size, seq_len, vocab_size]
        target: token ids of shape [batch_size, seq_len]
        ignore_index: target id excluded from the score (e.g. padding)

    Example:
        >>> from torchmetrics_tpu.functional import perplexity
        >>> import jax.numpy as jnp
        >>> probs = jnp.full((1, 4, 6), 1 / 6)
        >>> target = jnp.asarray([[0, 1, 2, 3]])
        >>> result = perplexity(probs, target)
        >>> round(float(result), 4)
        6.0
    """
    total, count = _perplexity_update(jnp.asarray(preds), jnp.asarray(target), ignore_index)
    return _perplexity_compute(total, count)
