"""Translation Edit Rate (TER).

Reference: functional/text/ter.py (600 LoC), which follows tercom via
sacrebleu's lib_ter. TER = (#shifts + word edit distance) / avg reference
length, where shifts greedily move a contiguous misaligned phrase of the
hypothesis to its reference position while that reduces edit distance.

Re-implemented here from the tercom algorithm description: a trace-producing
Levenshtein (helper.py) drives alignment; the shift search enumerates matching
phrase pairs (capped like tercom: size ≤ 10, distance ≤ 50, ≤ 1000 candidates)
and ranks candidates by (edit gain, length, earliest). States: two psum-able
scalars (total edits, total reference length).
"""
from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.helper import _LevenshteinEditDistance

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

# the reference removes ONLY this set (reference ter.py:180-182), not all of
# string.punctuation — tokens like <, >, #, - must survive no_punctuation
_PUNCT_RE = re.compile(r"[\.,\?:;!\"\(\)]")
_ASIAN_PUNCT = re.compile(r"([、。〈-】〔-〟｡-･・])")
_FULL_WIDTH_PUNCT = re.compile(r"([．，？：；！＂（）])")
_TERCOM_TOKENIZE_RE = (
    (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
    # possessive splitting, in the reference's rule order (reference ter.py:136-138)
    (re.compile(r"'s "), r" 's "),
    (re.compile(r"'s$"), r" 's"),
    (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
    (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
    (re.compile(r"([0-9])(-)"), r"\1 \2 "),
)


class _TercomTokenizer:
    """Tercom normalization/tokenization options (reference ter.py:71-188)."""

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = self._remove_punct(sentence)
            if self.asian_support:
                sentence = self._remove_asian_punct(sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = f" {sentence} "
        # NB the reference joins "\n-" (not the sgm-era "-\n") and has NO
        # <skipped> rule — it tokenizes that literally (reference ter.py:125-133)
        sentence = (
            sentence.replace("\n-", "")
            .replace("\n", " ")
            .replace("&quot;", '"')
            .replace("&amp;", "&")
            .replace("&lt;", "<")
            .replace("&gt;", ">")
        )
        for pattern, repl in _TERCOM_TOKENIZE_RE:
            sentence = pattern.sub(repl, sentence)
        return sentence

    @staticmethod
    def _normalize_asian(sentence: str) -> str:
        """Split ideographs to character level, kana runs kept joined —
        rule-for-rule the reference tokenizer (reference ter.py:152-176; its
        kana regexes are start-anchored and near-no-op, reproduced verbatim
        because tercom parity means matching them, quirks included)."""
        # CJK Unified Ideographs + Extension A
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        # CJK Strokes + Radicals Supplement
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        # CJK Compatibility (+Ideographs, +Forms)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        # Enclosed CJK Letters and Months (reference's over-wide ㈀-㼢)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 ", sentence)
        sentence = _ASIAN_PUNCT.sub(r" \1 ", sentence)
        return _FULL_WIDTH_PUNCT.sub(r" \1 ", sentence)

    @staticmethod
    def _remove_punct(sentence: str) -> str:
        return _PUNCT_RE.sub("", sentence)

    @staticmethod
    def _remove_asian_punct(sentence: str) -> str:
        sentence = _ASIAN_PUNCT.sub("", sentence)
        return _FULL_WIDTH_PUNCT.sub("", sentence)


def _preprocess_sentence(sentence: str, tokenizer: _TercomTokenizer) -> str:
    return tokenizer(sentence.rstrip())


def _trace_to_alignment(trace: str) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Map the edit trace to ref→pred position alignment + per-side error flags.

    Reference ter.py's `_trace_to_alignment`. For each reference position the
    aligned prediction index (for 'e'/'s' steps); error flags mark positions
    touched by s/i/d ops.
    """
    pred_idx = ref_idx = -1
    alignments: Dict[int, int] = {}
    pred_errors: List[int] = []
    target_errors: List[int] = []
    for op in trace:
        if op == "e":  # keep
            pred_idx += 1
            ref_idx += 1
            alignments[ref_idx] = pred_idx
            pred_errors.append(0)
            target_errors.append(0)
        elif op == "s":
            pred_idx += 1
            ref_idx += 1
            alignments[ref_idx] = pred_idx
            pred_errors.append(1)
            target_errors.append(1)
        elif op == "i":  # extra pred token
            pred_idx += 1
            pred_errors.append(1)
        elif op == "d":  # missing pred token — still anchors to current pred pos
            ref_idx += 1
            alignments[ref_idx] = pred_idx
            target_errors.append(1)
    return alignments, target_errors, pred_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """All matching phrase pairs eligible to shift (tercom caps applied)."""
    for pred_start in range(len(pred_words)):
        for target_start in range(len(target_words)):
            if abs(pred_start - target_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if pred_words[pred_start + length - 1] != target_words[target_start + length - 1]:
                    break
                yield pred_start, target_start, length
                if len(pred_words) == pred_start + length or len(target_words) == target_start + length:
                    break


def _handle_corner_cases_during_shifting(
    alignments: Dict[int, int],
    pred_errors: List[int],
    target_errors: List[int],
    pred_start: int,
    target_start: int,
    length: int,
) -> bool:
    """True → skip this candidate (error-free span, or already aligned) — ter.py:244-278."""
    # no errors in either span → nothing to fix by shifting
    if sum(pred_errors[pred_start : pred_start + length]) == 0:
        return True
    if sum(target_errors[target_start : target_start + length]) == 0:
        return True
    # shifting within an already-aligned match is a no-op
    if pred_start <= alignments[target_start] < pred_start + length:
        return True
    return False


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move words[start:start+length] so it lands at position `target` (ter.py:281-312)."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    # target within the shifted span: rotate inside
    return (
        words[:start]
        + words[start + length : length + target]
        + words[start : start + length]
        + words[length + target :]
    )


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    cached_edit_distance: _LevenshteinEditDistance,
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of the greedy shift search (reference ter.py:315-395)."""
    edit_distance, trace = cached_edit_distance(pred_words)
    alignments, target_errors, pred_errors = _trace_to_alignment(trace)
    best: Optional[Tuple[int, int, int, int, List[str]]] = None

    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        if _handle_corner_cases_during_shifting(
            alignments, pred_errors, target_errors, pred_start, target_start, length
        ):
            continue
        prev_idx = -1
        for offset in range(-1, length):
            if target_start + offset == -1:
                idx = 0
            elif target_start + offset in alignments:
                idx = alignments[target_start + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted_words = _perform_shift(pred_words, pred_start, length, idx)
            candidate = (
                edit_distance - cached_edit_distance(shifted_words)[0],
                length,
                -pred_start,
                -idx,
                shifted_words,
            )
            checked_candidates += 1
            if best is None or candidate[:4] > best[:4]:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if best is None:
        return 0, pred_words, checked_candidates
    return best[0], best[4], checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> int:
    """Edits (shifts + Levenshtein) for one hypothesis/reference pair (ter.py:396-428)."""
    if len(target_words) == 0:
        return 0
    cached_edit_distance = _LevenshteinEditDistance(target_words)
    num_shifts = 0
    checked_candidates = 0
    input_words = list(pred_words)
    while True:
        delta, new_input_words, checked_candidates = _shift_words(
            input_words, target_words, cached_edit_distance, checked_candidates
        )
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words
    edit_distance, _ = cached_edit_distance(input_words)
    return num_shifts + edit_distance


def _compute_sentence_statistics(
    pred_words: List[str], target_words_list: List[List[str]]
) -> Tuple[float, float]:
    """Best edits over references + avg reference length (ter.py:431-455)."""
    tgt_lengths = 0.0
    best_num_edits = float(int(2e16))
    for tgt_words in target_words_list:
        # argument order mirrors the reference (ter.py:449): the Levenshtein
        # cache is built on the prediction and the reference words are shifted
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    avg_tgt_len = tgt_lengths / len(target_words_list)
    return best_num_edits, avg_tgt_len


def _compute_ter_score_from_statistics(num_edits: Array, tgt_length: Array) -> Array:
    """num_edits/avg_len with the degenerate-length conventions (ter.py:458-473)."""
    return jnp.where(
        tgt_length > 0,
        num_edits / jnp.maximum(tgt_length, 1e-16),
        jnp.where(num_edits > 0, 1.0, 0.0),
    )


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
    total_num_edits: Array,
    total_tgt_length: Array,
    sentence_ter: Optional[List[Array]] = None,
) -> Tuple[Array, Array, Optional[List[Array]]]:
    """Accumulate corpus edits + lengths (reference ter.py:476-517)."""
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds_l) != len(target_l):
        raise ValueError(f"Corpus has different size {len(preds_l)} != {len(target_l)}")
    for pred, tgt in zip(preds_l, target_l):
        tgt_words_ = [_preprocess_sentence(t, tokenizer).split() for t in tgt]
        pred_words_ = _preprocess_sentence(pred, tokenizer).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        total_num_edits = total_num_edits + num_edits
        total_tgt_length = total_tgt_length + tgt_length
        if sentence_ter is not None:
            sentence_ter.append(
                _compute_ter_score_from_statistics(jnp.asarray(num_edits), jnp.asarray(tgt_length))
            )
    return total_num_edits, total_tgt_length, sentence_ter


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    return _compute_ter_score_from_statistics(total_num_edits, total_tgt_length)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """TER of translated text against references (reference ter.py:534-600).

    Example:
        >>> from torchmetrics_tpu.functional import translation_edit_rate
        >>> import jax.numpy as jnp
        >>> preds = ["the cat sat on the mat"]
        >>> target = [["a cat sat on the mat"]]
        >>> result = translation_edit_rate(preds, target)
        >>> round(float(result), 4)
        0.1667
    """
    if not isinstance(normalize, bool):
        raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
    if not isinstance(no_punctuation, bool):
        raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
    if not isinstance(lowercase, bool):
        raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
    if not isinstance(asian_support, bool):
        raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")

    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits = jnp.asarray(0.0)
    total_tgt_length = jnp.asarray(0.0)
    sentence_ter: Optional[List[Array]] = [] if return_sentence_level_score else None
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(
        preds, target, tokenizer, total_num_edits, total_tgt_length, sentence_ter
    )
    corpus = _ter_compute(total_num_edits, total_tgt_length)
    if return_sentence_level_score and sentence_ter is not None:
        return corpus, jnp.stack(sentence_ter) if sentence_ter else jnp.zeros(0)
    return corpus
