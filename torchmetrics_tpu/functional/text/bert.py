"""BERTScore with a pluggable embedding model.

Reference: functional/text/bert.py:243-447 — contextual embeddings of candidate
and reference sentences, token-pair cosine similarities, greedy matching with
optional IDF weighting.

TPU design: the *model* is a hook. `user_model` is any callable mapping a list
of sentences to ``(embeddings [N, L, D], mask [N, L])`` — optionally the
extended triple ``(embeddings, mask, token_ids [N, L])`` so IDF weights align
with subword positions (the reference's own escape hatch, bert.py:76-77 +
examples/bert_score-own_model.py) — typically a flax encoder jitted once and
shared. When `user_model` is omitted we fall back
to a HF `transformers` AutoModel on host torch if that wheel + weights are
available locally (no downloads are attempted). All post-model math — cosine
similarity matrices, greedy max matching, IDF weighting — is pure jnp and runs
on device, batched over sentence pairs with static padded shapes.
"""
from __future__ import annotations

import math
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array


def _simple_tokenize(text: str) -> List[str]:
    return text.lower().split()


def _compute_idf(corpus: Sequence[str], tokenizer: Callable[[str], List[str]]) -> Dict[str, float]:
    """Smoothed IDF over the reference corpus (reference bert.py:202-214)."""
    num_docs = len(corpus)
    df: Counter = Counter()
    for doc in corpus:
        df.update(set(tokenizer(doc)))
    return {tok: math.log((num_docs + 1) / (cnt + 1)) for tok, cnt in df.items()}


def _greedy_cosine_scores(
    pred_emb: Array,  # [Lp, D]
    pred_mask: Array,  # [Lp]
    target_emb: Array,  # [Lt, D]
    target_mask: Array,  # [Lt]
    pred_idf: Array,  # [Lp]
    target_idf: Array,  # [Lt]
) -> Tuple[Array, Array, Array]:
    """Greedy-matched precision/recall/f1 for one sentence pair — pure jnp.

    Reference bert.py `_get_precision_recall_f1`: every pred token greedily
    matches its most-similar target token (precision side) and vice versa
    (recall side); matches are IDF-weighted.
    """
    pred_norm = pred_emb / jnp.maximum(jnp.linalg.norm(pred_emb, axis=-1, keepdims=True), 1e-12)
    target_norm = target_emb / jnp.maximum(jnp.linalg.norm(target_emb, axis=-1, keepdims=True), 1e-12)
    sim = pred_norm @ target_norm.T  # [Lp, Lt] — MXU matmul
    neg = jnp.asarray(-1e9, sim.dtype)
    sim = jnp.where(pred_mask[:, None] & target_mask[None, :], sim, neg)

    pred_w = pred_idf * pred_mask
    target_w = target_idf * target_mask
    precision = jnp.sum(jnp.max(sim, axis=1) * pred_w) / jnp.maximum(jnp.sum(pred_w), 1e-12)
    recall = jnp.sum(jnp.max(sim, axis=0) * target_w) / jnp.maximum(jnp.sum(target_w), 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    return precision, recall, f1


def _default_transformers_embedder(
    model_name_or_path: str, max_length: int
) -> Callable[[List[str]], Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Host-side HF encoder (torch CPU), local weights only (bert.py:359-360).

    Returns the extended 3-tuple ``(embeddings, mask, token_ids)``; special
    tokens ([CLS]/[SEP]/pad) are masked out of the matching, mirroring the
    reference's `_process_attention_mask_for_special_tokens`
    (helper_embedding_metric.py).
    """
    try:
        import torch
        from transformers import AutoModel, AutoTokenizer
    except ImportError as err:  # pragma: no cover
        raise ModuleNotFoundError(
            "`bert_score` needs either a `user_model` callable or the `transformers` package with local weights."
        ) from err
    tok = AutoTokenizer.from_pretrained(model_name_or_path, local_files_only=True)
    model = AutoModel.from_pretrained(model_name_or_path, local_files_only=True)
    model.eval()
    special_ids = set(tok.all_special_ids)

    def embed(sentences: List[str]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        with torch.no_grad():
            enc = tok(sentences, return_tensors="pt", padding=True, truncation=True, max_length=max_length)
            out = model(**enc).last_hidden_state
        ids = enc["input_ids"].numpy()
        mask = enc["attention_mask"].numpy().astype(bool)
        for sid in special_ids:
            mask &= ids != sid
        return out.numpy(), mask, ids

    return embed


def bert_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Any] = None,
    user_model: Optional[Callable[[List[str]], Tuple[Any, Any]]] = None,
    user_tokenizer: Optional[Callable[[str], List[str]]] = None,
    verbose: bool = False,
    idf: bool = False,
    max_length: int = 512,
    batch_size: int = 64,
    rescale_with_baseline: bool = False,
    baseline: Optional[Array] = None,
) -> Dict[str, Array]:
    """BERTScore precision/recall/f1 (reference bert.py:243-447).

    Args:
        preds: candidate sentence(s).
        target: reference sentence(s).
        user_model: callable ``sentences -> (embeddings [N,L,D], mask [N,L])``;
            the TPU-native path — supply a jitted flax encoder.
        model_name_or_path: HF model id/path for the fallback host embedder.
        idf: weight token matches by reference-corpus IDF.
        rescale_with_baseline: linear rescale ``(s - b) / (1 - b)`` with a
            user-supplied ``baseline`` triple (the reference downloads baseline
            files; here they must be passed in).
    """
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [target] if isinstance(target, str) else list(target)
    if len(preds_l) != len(target_l):
        raise ValueError(f"Number of predicted and reference sentences must match: {len(preds_l)} != {len(target_l)}")
    if not preds_l:
        return {"precision": jnp.zeros(0), "recall": jnp.zeros(0), "f1": jnp.zeros(0)}

    if user_model is None:
        user_model = _default_transformers_embedder(model_name_or_path or "roberta-large", max_length)

    # hook protocol: (emb, mask) or the extended (emb, mask, token_ids);
    # token ids keep IDF weights aligned with subword positions.
    pred_out = user_model(preds_l)
    target_out = user_model(target_l)
    pred_ids = np.asarray(pred_out[2]) if len(pred_out) > 2 else None
    target_ids = np.asarray(target_out[2]) if len(target_out) > 2 else None
    pred_emb = jnp.asarray(pred_out[0])
    target_emb = jnp.asarray(target_out[0])
    pred_mask = jnp.asarray(pred_out[1], dtype=bool)
    target_mask = jnp.asarray(target_out[1], dtype=bool)

    if idf:
        if target_ids is not None and pred_ids is not None:
            # id-keyed IDF over the reference corpus (reference
            # helper_embedding_metric.py:232: tokens_idf from the model's ids),
            # broadcast onto each position via its own token id
            tmask = np.asarray(target_out[1], dtype=bool)
            num_docs = len(target_l)
            df: Counter = Counter()
            for row, mrow in zip(target_ids, tmask):
                df.update(set(row[mrow].tolist()))
            default_idf = math.log(num_docs + 1)
            idf_map_ids = {tid: math.log((num_docs + 1) / (cnt + 1)) for tid, cnt in df.items()}

            def ids_to_idf(ids_mat: np.ndarray) -> np.ndarray:
                out = np.full(ids_mat.shape, default_idf, dtype=np.float32)
                for (i, j), tid in np.ndenumerate(ids_mat):
                    out[i, j] = idf_map_ids.get(int(tid), default_idf)
                return out

            pred_idf = jnp.asarray(ids_to_idf(pred_ids))
            target_idf = jnp.asarray(ids_to_idf(target_ids))
        else:
            # 2-tuple hook: fall back to word-level IDF, positions assumed to
            # follow `user_tokenizer` order (document the contract)
            tok_fn = user_tokenizer or _simple_tokenize
            idf_map = _compute_idf(target_l, tok_fn)
            max_lp = pred_emb.shape[1]
            max_lt = target_emb.shape[1]

            def idf_row(sent: str, width: int) -> np.ndarray:
                toks = tok_fn(sent)[:width]
                row = np.ones(width, dtype=np.float32)
                for i, t in enumerate(toks):
                    row[i] = idf_map.get(t, math.log(len(target_l) + 1))
                return row

            pred_idf = jnp.asarray(np.stack([idf_row(s, max_lp) for s in preds_l]))
            target_idf = jnp.asarray(np.stack([idf_row(s, max_lt) for s in target_l]))
    else:
        pred_idf = jnp.ones(pred_emb.shape[:2])
        target_idf = jnp.ones(target_emb.shape[:2])

    import jax

    p, r, f = jax.vmap(_greedy_cosine_scores)(pred_emb, pred_mask, target_emb, target_mask, pred_idf, target_idf)
    if rescale_with_baseline:
        if baseline is None:
            raise ValueError(
                "`rescale_with_baseline` requires a `baseline` array [precision_b, recall_b, f1_b]"
                " (the reference downloads baseline files; zero-egress builds must pass them explicitly)."
            )
        b = jnp.asarray(baseline)
        p = (p - b[0]) / (1 - b[0])
        r = (r - b[1]) / (1 - b[1])
        f = (f - b[2]) / (1 - b[2])
    return {"precision": p, "recall": r, "f1": f}
