"""Shared host-side text helpers: Levenshtein DP, n-gram counting.

Reference: functional/text/helper.py:54-295 (`_LevenshteinEditDistance` with row
caching) and functional/text/bleu.py:19-45 (`_count_ngram`). TPU stance: string
processing is inherently host work in the reference too — the device only ever
sees the scalar counters these helpers produce. We therefore keep a lean pure-
Python DP (no torch/Tensor round-trips per token, unlike the reference) and
return plain ints that the callers fold into jnp accumulator states.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence, Tuple, Union

_INT_INFINITY = int(1e16)


def _batch_distances(preds: Sequence[str], target: Sequence[str], char_level: bool = False):
    """Tokenize every (pred, target) pair and run ONE batched C++ Levenshtein call.

    One ctypes crossing for the whole batch (native/edit_distance.cpp
    tm_levenshtein_batch) instead of a per-pair call — the per-call overhead
    dominates for typical sentence lengths. Returns (token pairs, distances).
    """
    from torchmetrics_tpu.native import batch_edit_distance

    if char_level:
        pairs = [(list(p_), list(t_)) for p_, t_ in zip(preds, target)]
    else:
        pairs = [(p_.split(), t_.split()) for p_, t_ in zip(preds, target)]
    return pairs, batch_edit_distance(pairs)


def _edit_distance(prediction_tokens: Sequence, reference_tokens: Sequence, substitution_cost: int = 1) -> int:
    """Word/char-level Levenshtein distance.

    Reference functional/text/helper.py:297-320 (`_edit_distance` free function);
    dispatches to the first-party C++ kernel (native/edit_distance.cpp) with a
    pure-Python two-row DP fallback.
    """
    from torchmetrics_tpu.native import edit_distance as _native_edit_distance

    return _native_edit_distance(prediction_tokens, reference_tokens, substitution_cost)


class _LevenshteinEditDistance:
    """Edit distance against a fixed reference with full trace, for TER shifts.

    Reference functional/text/helper.py:54-295, itself following sacrebleu's
    lib_ter: a beam-constrained DP (width 25 around the length-ratio pseudo-
    diagonal) with tie preference substitute/keep → consume-prediction →
    consume-reference, whose backtracked trace is then *flipped* so that in
    the returned string ``'i'`` consumes a hypothesis token and ``'d'``
    consumes a reference token. Exact tie-breaking matters: the TER shift
    heuristics read alignments off this trace, so every choice here mirrors
    the reference (we only drop its row cache — plain host DP is fast enough
    at sentence scale).

    ``__call__(pred_tokens) -> (distance, trace)``; trace chars:
    ``'e'`` keep, ``'s'`` substitute, ``'i'`` hyp-consume, ``'d'`` ref-consume.
    """

    _BEAM_WIDTH = 25
    _INF = _INT_INFINITY

    def __init__(self, reference_tokens: List[str], op_insert: int = 1, op_delete: int = 1, op_substitute: int = 1) -> None:
        self.reference_tokens = reference_tokens
        self.reference_len = len(reference_tokens)
        self.op_insert = op_insert
        self.op_delete = op_delete
        self.op_substitute = op_substitute

    def __call__(self, prediction_tokens: List[str]) -> Tuple[int, str]:
        import math

        m, n = len(prediction_tokens), self.reference_len
        # cells: (cost, op) with op in pre-flip convention:
        # 'd' consumes a prediction token (row step), 'i' a reference token
        dist = [[(self._INF, "?")] * (n + 1) for _ in range(m + 1)]
        dist[0] = [(j * self.op_insert, "i") for j in range(n + 1)]
        length_ratio = n / m if prediction_tokens else 1.0
        beam = (
            math.ceil(length_ratio / 2 + self._BEAM_WIDTH)
            if length_ratio / 2 > self._BEAM_WIDTH
            else self._BEAM_WIDTH
        )
        for i in range(1, m + 1):
            pseudo_diag = math.floor(i * length_ratio)
            min_j = max(0, pseudo_diag - beam)
            max_j = n + 1 if i == m else min(n + 1, pseudo_diag + beam)
            p_tok = prediction_tokens[i - 1]
            for j in range(min_j, max_j):
                if j == 0:
                    dist[i][j] = (dist[i - 1][j][0] + self.op_delete, "d")
                else:
                    if p_tok == self.reference_tokens[j - 1]:
                        cost_sub, op_sub = self.op_nothing, "e"
                    else:
                        cost_sub, op_sub = self.op_substitute, "s"
                    best = (dist[i - 1][j - 1][0] + cost_sub, op_sub)
                    cand = dist[i - 1][j][0] + self.op_delete
                    if cand < best[0]:
                        best = (cand, "d")
                    cand = dist[i][j - 1][0] + self.op_insert
                    if cand < best[0]:
                        best = (cand, "i")
                    dist[i][j] = best
        # backtrack, then flip i<->d (rewrite b->a instead of a->b;
        # reference helper.py:353-379)
        trace = []
        i, j = m, n
        while i > 0 or j > 0:
            op = dist[i][j][1]
            trace.append(op)
            if op in ("e", "s"):
                i, j = i - 1, j - 1
            elif op == "d":
                i -= 1
            elif op == "i":
                j -= 1
            else:  # beam left this cell unreached; cannot happen on valid paths
                raise RuntimeError("edit-distance backtrack escaped the beam")
        flip = {"i": "d", "d": "i"}
        return dist[m][n][0], "".join(flip.get(op, op) for op in reversed(trace))

    @property
    def op_nothing(self) -> int:
        return 0


def _count_ngrams(tokens: Sequence, max_n: int) -> Counter:
    """All n-gram counts for n in [1, max_n] (reference bleu.py:26-45)."""
    counter: Counter = Counter()
    for n in range(1, max_n + 1):
        for j in range(len(tokens) - n + 1):
            counter[tuple(tokens[j : j + n])] += 1
    return counter


def _ngram_counts_by_order(tokens: Sequence, max_n: int) -> Dict[int, Counter]:
    """Per-order n-gram counts {n: Counter} (reference chrf.py:134-149)."""
    out: Dict[int, Counter] = {n: Counter() for n in range(1, max_n + 1)}
    for n in range(1, max_n + 1):
        c = out[n]
        for j in range(len(tokens) - n + 1):
            c[tuple(tokens[j : j + n])] += 1
    return out


def _validate_text_inputs(
    preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]
) -> Tuple[Sequence[str], Sequence[str]]:
    preds = [preds] if isinstance(preds, str) else list(preds)
    target = [target] if isinstance(target, str) else list(target)
    if len(preds) != len(target):
        raise ValueError(
            f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
        )
    return preds, target
