"""ROUGE score (rouge-1..9, rougeL, rougeLsum).

Reference: functional/text/rouge.py (524 LoC), itself following the official
google-research rouge_scorer. Per-sentence precision/recall/fmeasure with
multi-reference accumulation ('best' by fmeasure of the first key / 'avg').

Host-side text work; per-sentence scores are stacked into jnp arrays so the
modular class can keep them as `cat` list states and mean-reduce on compute.
Sentence splitting for Lsum uses a regex splitter (the reference requires the
`nltk` wheel, rouge.py:62-71 — not bundled here); a custom splitter can be
passed through the `sentence_splitter` hook.
"""
from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1, "rouge2": 2, "rouge3": 3, "rouge4": 4, "rouge5": 5,
    "rouge6": 6, "rouge7": 7, "rouge8": 8, "rouge9": 9, "rougeL": "L", "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")

_SENTENCE_RE = re.compile(r"(?<=[.!?])\s+")


def _split_sentence(x: str) -> Sequence[str]:
    """Regex sentence splitter (stand-in for nltk.sent_tokenize, rouge.py:62-71)."""
    x = re.sub("<n>", "", x)
    return [s for s in _SENTENCE_RE.split(x.strip()) if s]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    """precision/recall/fmeasure triple (reference rouge.py:74-92).

    Plain floats: scores are per-sentence host values (hundreds per call), so
    materialising a device scalar each would dominate the runtime; they become
    one array at aggregation time.
    """
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    fmeasure = 2 * precision * recall / (precision + recall)
    return {"precision": precision, "recall": recall, "fmeasure": fmeasure}


def _lcs_table(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> List[List[int]]:
    table = [[0] * (len(target_tokens) + 1) for _ in range(len(pred_tokens) + 1)]
    for i in range(1, len(pred_tokens) + 1):
        for j in range(1, len(target_tokens) + 1):
            if pred_tokens[i - 1] == target_tokens[j - 1]:
                table[i][j] = table[i - 1][j - 1] + 1
            else:
                table[i][j] = max(table[i - 1][j], table[i][j - 1])
    return table


def _lcs(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    """Length of the longest common subsequence (reference rouge.py:95-115).

    Dispatches to the first-party C++ kernel (native/edit_distance.cpp:tm_lcs)
    — the Python DP table is only built when a backtracked LCS is needed
    (rougeLsum) or the toolchain is unavailable.
    """
    from torchmetrics_tpu.native import lcs_length

    return lcs_length(pred_tokens, target_tokens)


def _backtracked_lcs_indices(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> List[int]:
    """Indices into target of one LCS (reference rouge.py:118-141)."""
    table = _lcs_table(pred_tokens, target_tokens)
    i, j = len(pred_tokens), len(target_tokens)
    indices: List[int] = []
    while i > 0 and j > 0:
        if pred_tokens[i - 1] == target_tokens[j - 1]:
            indices.append(j - 1)
            i -= 1
            j -= 1
        elif table[i - 1][j] >= table[i][j - 1]:
            i -= 1
        else:
            j -= 1
    return indices[::-1]


def _union_lcs(pred_tokens_list: Sequence[Sequence[str]], target_tokens: Sequence[str]) -> Sequence[str]:
    """Tokens of the union-LCS of a target sentence vs all pred sentences (rouge.py:144-163)."""
    union: set = set()
    for pred_tokens in pred_tokens_list:
        union |= set(_backtracked_lcs_indices(pred_tokens, target_tokens))
    return [target_tokens[i] for i in sorted(union)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """Lowercase alnum normalization + split + optional stemming (rouge.py:166-199)."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_l_score(pred: Sequence[str], target: Sequence[str], lcs: Optional[int] = None) -> Dict[str, Array]:
    """Rouge-L triple (reference rouge.py:228-241).

    ``lcs`` carries a precomputed LCS length from the batched native kernel
    (see ``_rouge_score_update``); without it the per-pair path is used.
    """
    pred_len, target_len = len(pred), len(target)
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    return _compute_metrics(lcs if lcs is not None else _lcs(pred, target), pred_len, target_len)


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, Array]:
    """Rouge-Lsum via union-LCS over sentences (reference rouge.py:244-284)."""
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}

    def _get_token_counts(sentences: Sequence[Sequence[str]]) -> Counter:
        ngrams: Counter = Counter()
        for sentence in sentences:
            ngrams.update(sentence)
        return ngrams

    pred_tokens_count = _get_token_counts(pred)
    target_tokens_count = _get_token_counts(target)
    hits = 0
    for tgt in target:
        lcs = _union_lcs(pred, tgt)
        for token in lcs:
            if pred_tokens_count[token] > 0 and target_tokens_count[token] > 0:
                hits += 1
                pred_tokens_count[token] -= 1
                target_tokens_count[token] -= 1
    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    sentence_splitter: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, Array]]]:
    """Per-sentence scores with multi-ref accumulation (reference rouge.py:287-399).

    Two passes: tokenize every (pred, target) pair first, so the ROUGE-L LCS
    lengths for the whole batch go through ONE native kernel crossing
    (native/edit_distance.cpp:tm_lcs_batch) instead of a Python DP per pair.
    """
    split_fn = sentence_splitter or _split_sentence
    results: Dict[Union[int, str], List[Dict[str, Array]]] = {k: [] for k in rouge_keys_values}

    def _tok(text: str) -> Sequence[str]:
        return _normalize_and_tokenize_text(text, stemmer, normalizer, tokenizer)

    tokenized: List[Tuple[Sequence[str], List[Sequence[str]], List[Tuple[Sequence[str], List[Sequence[str]]]]]] = []
    for pred_raw, target_raw in zip(preds, target):
        target_list = [target_raw] if isinstance(target_raw, str) else list(target_raw)
        pred = _tok(pred_raw)
        pred_lsum: List[Sequence[str]] = []
        if "Lsum" in rouge_keys_values:
            pred_lsum = [_tok(s) for s in split_fn(pred_raw)]
        tgt_entries: List[Tuple[Sequence[str], List[Sequence[str]]]] = []
        for target_raw_inner in target_list:
            tgt = _tok(target_raw_inner)
            tgt_lsum: List[Sequence[str]] = []
            if "Lsum" in rouge_keys_values:
                tgt_lsum = [_tok(s) for s in split_fn(target_raw_inner)]
            tgt_entries.append((tgt, tgt_lsum))
        tokenized.append((pred, pred_lsum, tgt_entries))

    # the LCS lengths and clipped n-gram overlaps for the whole batch each go
    # through ONE native kernel crossing; results are indexed by pair position
    # so repeated keys in rouge_keys_values read the same precomputed entry
    all_pairs = [(pred, tgt) for pred, _, tgt_entries in tokenized for tgt, _ in tgt_entries]
    lcs_by_pair: List[Optional[int]] = []
    if "L" in rouge_keys_values:
        from torchmetrics_tpu.native import batch_lcs

        nonempty = [(a, b) for a, b in all_pairs if a and b]
        it = iter(batch_lcs(nonempty).tolist())
        lcs_by_pair = [int(next(it)) if (a and b) else None for a, b in all_pairs]

    ngram_by_pair: Dict[int, List[Tuple[int, int, int]]] = {}
    int_keys = sorted({k for k in rouge_keys_values if isinstance(k, int)})
    if int_keys:
        from torchmetrics_tpu.native import batch_ngram_hits_multi

        per_n = batch_ngram_hits_multi(all_pairs, int_keys)
        for n in int_keys:
            ngram_by_pair[n] = list(zip(*(arr.tolist() for arr in per_n[n])))

    pair_idx = 0
    for pred, pred_lsum, tgt_entries in tokenized:
        list_results: List[Dict[Union[int, str], Dict[str, Array]]] = []
        for tgt, tgt_lsum in tgt_entries:
            result_inner: Dict[Union[int, str], Dict[str, Array]] = {}
            for rouge_key in rouge_keys_values:
                if isinstance(rouge_key, int):
                    hits, pred_len, target_len = ngram_by_pair[rouge_key][pair_idx]
                    if 0 in (pred_len, target_len):
                        score = {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
                    else:
                        score = _compute_metrics(hits, pred_len, target_len)
                elif rouge_key == "L":
                    score = _rouge_l_score(pred, tgt, lcs=lcs_by_pair[pair_idx])
                else:  # Lsum
                    score = _rouge_lsum_score(pred_lsum, tgt_lsum)
                result_inner[rouge_key] = score
            list_results.append(result_inner)
            pair_idx += 1

        if accumulate == "best":
            key_curr = rouge_keys_values[0]
            all_fmeasure = [float(v[key_curr]["fmeasure"]) for v in list_results]
            highest_idx = max(range(len(all_fmeasure)), key=all_fmeasure.__getitem__)
            for rouge_key in rouge_keys_values:
                results[rouge_key].append(list_results[highest_idx][rouge_key])
        elif accumulate == "avg":
            for rouge_key in rouge_keys_values:
                avg = {
                    t: sum(r[rouge_key][t] for r in list_results) / len(list_results)
                    for t in ("precision", "recall", "fmeasure")
                }
                results[rouge_key].append(avg)
        else:
            raise ValueError(f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}")
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Array]]) -> Dict[str, Array]:
    """Mean over sentence-level scores (reference rouge.py:402-417)."""
    return {
        k: jnp.asarray(np.mean([float(x) for x in v]), dtype=jnp.float32) if len(v) else jnp.asarray(0.0)
        for k, v in sentence_results.items()
    }


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE score (reference rouge.py:420-524). Returns {key_precision/_recall/_fmeasure}.

    Example:
        >>> from torchmetrics_tpu.functional import rouge_score
        >>> preds = ["the cat sat on the mat"]
        >>> target = [["a cat sat on the mat"]]
        >>> result = rouge_score(preds, target)
        >>> round(float(result['rouge1_fmeasure']), 4)
        0.8333
    """
    if use_stemmer:
        raise ValueError(
            "Stemming requires the `nltk` PorterStemmer which is not bundled; pass a custom `normalizer` instead."
        )
    stemmer = None

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate=accumulate,
        stemmer=stemmer, normalizer=normalizer, tokenizer=tokenizer,
    )
    output: Dict[str, List[Array]] = {
        f"rouge{k}_{t}": [] for k in rouge_keys_values for t in ("fmeasure", "precision", "recall")
    }
    for rouge_key, metrics in sentence_results.items():
        for metric in metrics:
            for t, value in metric.items():
                output[f"rouge{rouge_key}_{t}"].append(value)
    return _rouge_score_compute(output)
