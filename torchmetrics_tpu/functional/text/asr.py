"""Speech-recognition error rates: WER, CER, MER, WIL, WIP.

Reference: functional/text/{wer,cer,mer,wil,wip}.py — each is host-side
Levenshtein counting into two/three scalar accumulators, divided at compute.
States are jnp scalars so the modular classes psum-sync them over the mesh.
"""
from __future__ import annotations

import math
from typing import List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.helper import _batch_distances, _validate_text_inputs


def _host_div(num: Union[Array, float], den: Union[Array, float]) -> Union[Array, float]:
    """Division with IEEE zero semantics on host floats (0/0 -> nan, x/0 -> inf),
    matching the jnp behavior the modular (array-state) path gets for free."""
    if isinstance(num, (int, float)) and isinstance(den, (int, float)):
        if den == 0.0:
            return float("nan") if num == 0.0 else math.copysign(math.inf, num)
        return num / den
    return num / den


# ------------------------------------------------------------------------- WER
def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[float, float]:
    """Summed word-level edit distance + total reference words (reference wer.py:23-48).

    Returns host floats: the counts fold into device state (or the final ratio)
    with zero per-call host->device transfers — a scalar put per update would
    dominate the whole text pipeline on a TPU tunnel.
    """
    preds, target = _validate_text_inputs(preds, target)
    pairs, dists = _batch_distances(preds, target)
    return float(dists.sum()), float(sum(len(t) for _, t in pairs))


def _wer_compute(errors: Union[Array, float], total: Union[Array, float]) -> Array:
    return jnp.asarray(_host_div(errors, total), dtype=jnp.float32)


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WER = (S + D + I) / N over the reference words (reference wer.py:51-87).

    Example:
        >>> from torchmetrics_tpu.functional import word_error_rate
        >>> round(float(word_error_rate(["this is the answer"], ["this was the answer"])), 4)
        0.25
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)


# ------------------------------------------------------------------------- CER
def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[float, float]:
    """Char-level edit distance + total reference chars (reference cer.py:22-48);
    host floats like :func:`_wer_update`."""
    preds, target = _validate_text_inputs(preds, target)
    pairs, dists = _batch_distances(preds, target, char_level=True)
    return float(dists.sum()), float(sum(len(t) for _, t in pairs))


def _cer_compute(errors: Union[Array, float], total: Union[Array, float]) -> Array:
    return jnp.asarray(_host_div(errors, total), dtype=jnp.float32)


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """CER over reference characters (reference cer.py:51-87).

    Example:
        >>> from torchmetrics_tpu.functional import char_error_rate
        >>> import jax.numpy as jnp
        >>> preds = ["this is the answer", "hello duck"]
        >>> target = ["this was the answer", "hello world"]
        >>> result = char_error_rate(preds, target)
        >>> round(float(result), 4)
        0.2333
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)


# ------------------------------------------------------------------------- MER
def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[float, float]:
    """Edit distance + max(len) totals (reference mer.py:23-50); host floats
    like :func:`_wer_update`."""
    preds, target = _validate_text_inputs(preds, target)
    pairs, dists = _batch_distances(preds, target)
    return float(dists.sum()), float(sum(max(len(p_), len(t_)) for p_, t_ in pairs))


def _mer_compute(errors: Union[Array, float], total: Union[Array, float]) -> Array:
    return jnp.asarray(_host_div(errors, total), dtype=jnp.float32)


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Match error rate (reference mer.py:66-91).

    Example:
        >>> from torchmetrics_tpu.functional import match_error_rate
        >>> import jax.numpy as jnp
        >>> preds = ["this is the answer", "hello duck"]
        >>> target = ["this was the answer", "hello world"]
        >>> result = match_error_rate(preds, target)
        >>> round(float(result), 4)
        0.3333
    """
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)


# --------------------------------------------------------------------- WIL/WIP
def _word_info_update(
    preds: Union[str, List[str]], target: Union[str, List[str]]
) -> Tuple[float, float, float]:
    """Negated hit count + per-side word totals.

    Reference wil.py:22-54 / wip.py:22-54: accumulates ``edit - max_len`` (the
    negative of the aligned-hit count; squared ratio cancels the sign),
    reference word total and prediction word total.
    """
    preds, target = _validate_text_inputs(preds, target)
    pairs, dists = _batch_distances(preds, target)
    errors = float(dists.sum())
    target_total = float(sum(len(t_) for _, t_ in pairs))
    preds_total = float(sum(len(p_) for p_, _ in pairs))
    total = float(sum(max(len(p_), len(t_)) for p_, t_ in pairs))
    return errors - total, target_total, preds_total


def _wil_compute(
    errors: Union[Array, float], target_total: Union[Array, float], preds_total: Union[Array, float]
) -> Array:
    return jnp.asarray(1 - (_host_div(errors, target_total) * _host_div(errors, preds_total)), dtype=jnp.float32)


def _wip_compute(
    errors: Union[Array, float], target_total: Union[Array, float], preds_total: Union[Array, float]
) -> Array:
    return jnp.asarray(_host_div(errors, target_total) * _host_div(errors, preds_total), dtype=jnp.float32)


def word_information_lost(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WIL = 1 - (H/N_ref)(H/N_hyp) (reference wil.py:57-94).

    Example:
        >>> from torchmetrics_tpu.functional import word_information_lost
        >>> import jax.numpy as jnp
        >>> preds = ["this is the answer", "hello duck"]
        >>> target = ["this was the answer", "hello world"]
        >>> result = word_information_lost(preds, target)
        >>> round(float(result), 4)
        0.5556
    """
    errors, target_total, preds_total = _word_info_update(preds, target)
    return _wil_compute(errors, target_total, preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WIP = (H/N_ref)(H/N_hyp) (reference wip.py:57-93).

    Example:
        >>> from torchmetrics_tpu.functional import word_information_preserved
        >>> import jax.numpy as jnp
        >>> preds = ["this is the answer", "hello duck"]
        >>> target = ["this was the answer", "hello world"]
        >>> result = word_information_preserved(preds, target)
        >>> round(float(result), 4)
        0.4444
    """
    errors, target_total, preds_total = _word_info_update(preds, target)
    return _wip_compute(errors, target_total, preds_total)
