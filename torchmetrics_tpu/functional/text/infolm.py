"""InfoLM — information measures over masked-LM token distributions.

Reference: functional/text/infolm.py (657 LoC; Colombo et al. 2021). A masked
LM assigns each sentence a distribution over the vocabulary (IDF- or
length-weighted average of per-position masked predictions); the metric is an
information measure between the candidate and reference distributions.

TPU design: all nine information measures are pure-jnp vectorized functions
(batched over sentence pairs, vocab axis reduced on device). Getting the
distributions is the model's job: pass `user_model` — a callable mapping a
list of sentences to a ``[N, vocab]`` distribution matrix (e.g. a jitted flax
MLM pipeline) — or rely on the host `transformers` fallback with local
weights (zero-egress: no downloads are attempted).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

_ALLOWED_INFORMATION_MEASURE = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)


class _InformationMeasure:
    """Vectorized information measures (reference infolm.py:72-296).

    ``__call__(preds_distribution [N,V], target_distribution [N,V]) -> [N]``.
    """

    def __init__(self, information_measure: str, alpha: Optional[float] = None, beta: Optional[float] = None) -> None:
        if information_measure not in _ALLOWED_INFORMATION_MEASURE:
            raise ValueError(
                f"Argument `information_measure` expected one of {_ALLOWED_INFORMATION_MEASURE}, got {information_measure}"
            )
        self.information_measure = information_measure
        needs_alpha = ("alpha_divergence", "ab_divergence", "renyi_divergence")
        if information_measure in needs_alpha and not isinstance(alpha, float):
            raise ValueError(f"Parameter `alpha` is expected to be defined for {information_measure}.")
        if information_measure in ("beta_divergence", "ab_divergence") and not isinstance(beta, float):
            raise ValueError(f"Parameter `beta` is expected to be defined for {information_measure}.")
        if information_measure == "alpha_divergence" and (not isinstance(alpha, float) or alpha in (0, 1)):
            raise ValueError(
                f"Parameter `alpha` is expected to be float differened from 0 and 1 for {information_measure}."
            )
        if information_measure == "beta_divergence" and (not isinstance(beta, float) or beta in (0, -1)):
            raise ValueError(
                f"Parameter `beta` is expected to be float differened from 0 and -1 for {information_measure}."
            )
        if information_measure == "ab_divergence" and (
            alpha is None or beta is None or 0 in (alpha, beta, alpha + beta)
        ):
            raise ValueError(
                f"Parameters `alpha`, `beta` and their sum are expected to be differened from 0 for {information_measure}."
            )
        if information_measure == "renyi_divergence" and (not isinstance(alpha, float) or alpha == 1):
            raise ValueError(f"Parameter `alpha` is expected to be float differened from 1 for {information_measure}.")
        self.alpha = alpha or 0.0
        self.beta = beta or 0.0

    def __call__(self, preds_distribution: Array, target_distribution: Array) -> Array:
        fn = getattr(self, f"_calculate_{self.information_measure}")
        return jnp.nan_to_num(fn(preds_distribution, target_distribution))

    @staticmethod
    def _calculate_kl_divergence(p: Array, t: Array) -> Array:
        return jnp.sum(t * jnp.log(p / t), axis=-1)

    def _calculate_alpha_divergence(self, p: Array, t: Array) -> Array:
        alpha_denom = self.alpha * (self.alpha - 1)
        return (1 - jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / alpha_denom

    def _calculate_ab_divergence(self, p: Array, t: Array) -> Array:
        a = jnp.log(jnp.sum(t ** (self.beta + self.alpha), axis=-1)) / (self.beta * (self.beta + self.alpha))
        b = jnp.log(jnp.sum(p ** (self.beta + self.alpha), axis=-1)) / (self.alpha * (self.beta + self.alpha))
        c = jnp.log(jnp.sum(t**self.alpha * p**self.beta, axis=-1)) / (self.alpha * self.beta)
        return a + b - c

    def _calculate_beta_divergence(self, p: Array, t: Array) -> Array:
        self.alpha = 1.0
        return self._calculate_ab_divergence(p, t)

    def _calculate_renyi_divergence(self, p: Array, t: Array) -> Array:
        return jnp.log(jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / (self.alpha - 1)

    @staticmethod
    def _calculate_l1_distance(p: Array, t: Array) -> Array:
        return jnp.sum(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _calculate_l2_distance(p: Array, t: Array) -> Array:
        return jnp.sqrt(jnp.sum((t - p) ** 2, axis=-1))

    @staticmethod
    def _calculate_l_infinity_distance(p: Array, t: Array) -> Array:
        return jnp.max(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _calculate_fisher_rao_distance(p: Array, t: Array) -> Array:
        return 2 * jnp.arccos(jnp.clip(jnp.sum(jnp.sqrt(p * t), axis=-1), 0.0, 1.0))


def _default_transformers_mlm_distribution(
    model_name_or_path: str, max_length: int, idf: bool
) -> Callable[[List[str]], np.ndarray]:
    """Host-side masked-LM distribution builder (reference infolm.py:367-462)."""
    try:
        import torch
        from transformers import AutoModelForMaskedLM, AutoTokenizer
    except ImportError as err:  # pragma: no cover
        raise ModuleNotFoundError(
            "`infolm` needs either a `user_model` callable or the `transformers` package with local weights."
        ) from err
    tok = AutoTokenizer.from_pretrained(model_name_or_path, local_files_only=True)
    model = AutoModelForMaskedLM.from_pretrained(model_name_or_path, local_files_only=True)
    model.eval()

    def distribution(sentences: List[str]) -> np.ndarray:
        # IDF over this call's corpus (the functional path scopes IDF to its
        # inputs; dataset-level IDF is the class metric's job — reference
        # infolm.py:580): weight each masked position's prediction by the
        # IDF of the token it covers (reference infolm.py:409-419).
        df: dict = {}
        encodings = []
        with torch.no_grad():
            for sent in sentences:
                enc = tok(sent, return_tensors="pt", truncation=True, max_length=max_length)
                encodings.append(enc["input_ids"][0])
            if idf:
                import math as _math

                for ids in encodings:
                    for t in set(ids.tolist()):
                        df[t] = df.get(t, 0) + 1
                idf_map = {t: _math.log((len(sentences) + 1) / (cnt + 1)) for t, cnt in df.items()}
            out_rows = []
            for ids in encodings:
                n = ids.shape[0]
                # mask each non-special position in turn, weighted-average predictions
                rows, weights = [], []
                for pos in range(n):
                    if ids[pos].item() in tok.all_special_ids:
                        continue
                    masked = ids.clone()
                    masked[pos] = tok.mask_token_id
                    logits = model(masked.unsqueeze(0)).logits[0, pos]
                    rows.append(torch.softmax(logits, dim=-1))
                    weights.append(idf_map[ids[pos].item()] if idf else 1.0)
                if not rows:
                    rows = [torch.full((model.config.vocab_size,), 1.0 / model.config.vocab_size)]
                    weights = [1.0]
                w = torch.tensor(weights).unsqueeze(1)
                out_rows.append(((torch.stack(rows) * w).sum(0) / w.sum()).numpy())
        return np.stack(out_rows)

    return distribution


def infolm(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    model_name_or_path: str = "bert-base-uncased",
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    max_length: Optional[int] = None,
    user_model: Optional[Callable[[List[str]], Any]] = None,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """InfoLM score (reference infolm.py:545-657)."""
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [target] if isinstance(target, str) else list(target)
    if len(preds_l) != len(target_l):
        raise ValueError(f"Number of predicted and reference sentences must match: {len(preds_l)} != {len(target_l)}")
    measure = _InformationMeasure(information_measure, alpha, beta)
    if user_model is None:
        user_model = _default_transformers_mlm_distribution(model_name_or_path, max_length or 512, idf)
    preds_distribution = jnp.asarray(user_model(preds_l)) ** (1.0 / temperature)
    preds_distribution = preds_distribution / jnp.sum(preds_distribution, axis=-1, keepdims=True)
    target_distribution = jnp.asarray(user_model(target_l)) ** (1.0 / temperature)
    target_distribution = target_distribution / jnp.sum(target_distribution, axis=-1, keepdims=True)
    sentence_scores = measure(preds_distribution, target_distribution)
    corpus = sentence_scores.mean()
    if return_sentence_level_score:
        return corpus, sentence_scores
    return corpus
