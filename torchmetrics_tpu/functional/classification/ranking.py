"""Multilabel ranking metrics (reference functional/classification/ranking.py, 267 LoC).

coverage_error, label_ranking_average_precision, label_ranking_loss.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import _sigmoid_if_logits
from torchmetrics_tpu.utils.checks import _check_same_shape


def _rank_data_max(x: Array) -> Array:
    """Tie-aware descending 'max' rank: rank[l] = #{l' : x[l'] >= x[l]}.

    Matches scipy's rankdata(-x, method='max') used by sklearn's ranking metrics;
    the O(L²) pairwise compare is a single fused TPU kernel for typical L.
    """
    return (x[:, None, :] >= x[:, :, None]).sum(-1)


def _multilabel_ranking_format(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds).reshape(-1, num_labels).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1, num_labels)
    preds = _sigmoid_if_logits(preds)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, 0, target)
    return preds, target.astype(jnp.int32)


def _coverage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Per-sample: rank of the lowest-scored relevant label (reference :30-45)."""
    big = jnp.where(target == 1, preds, jnp.inf)
    min_relevant = big.min(-1, keepdims=True)
    coverage = (preds >= min_relevant).sum(-1).astype(jnp.float32)
    has_pos = (target == 1).any(-1)
    coverage = jnp.where(has_pos, coverage, 0.0)
    return coverage.sum(), jnp.asarray(preds.shape[0], dtype=jnp.float32)


def multilabel_coverage_error(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """multilabel coverage error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_coverage_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_coverage_error(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.6667
    """

    if validate_args:
        _check_same_shape(preds, target)
    preds, target = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    coverage, total = _coverage_error_update(preds, target)
    return coverage / total


def _label_ranking_average_precision_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Mean precision at each relevant label's rank (reference :95-130).

    Tie-aware: both the overall rank and the rank among relevant labels use the
    'max' convention (#labels with score >= this label's score).
    """
    n, L = preds.shape
    rel = target == 1
    rank = _rank_data_max(preds)  # (N, L)
    # rank among relevant: #{l' relevant : preds[l'] >= preds[l]}
    rank_among_rel = ((preds[:, None, :] >= preds[:, :, None]) & rel[:, None, :]).sum(-1)
    score_per_label = jnp.where(rel, rank_among_rel / rank, 0.0)
    n_rel = rel.sum(-1)
    per_sample = jnp.where(n_rel > 0, score_per_label.sum(-1) / jnp.where(n_rel == 0, 1, n_rel), 1.0)
    return per_sample.sum(), jnp.asarray(n, dtype=jnp.float32)


def multilabel_ranking_average_precision(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """multilabel ranking average precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_ranking_average_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_ranking_average_precision(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _check_same_shape(preds, target)
    preds, target = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    score, total = _label_ranking_average_precision_update(preds, target)
    return score / total


def _label_ranking_loss_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Average fraction of incorrectly ordered (relevant, irrelevant) pairs."""
    rel = target == 1
    n_rel = rel.sum(-1)
    n_irr = (~rel).sum(-1)
    # count pairs (r, i) with preds[r] <= preds[i]
    wrong = (
        (preds[:, None, :] >= preds[:, :, None]) & (rel[:, :, None] & ~rel[:, None, :])
    ).sum((-2, -1))
    denom = n_rel * n_irr
    per_sample = jnp.where(denom > 0, wrong / jnp.where(denom == 0, 1, denom), 0.0)
    return per_sample.sum(), jnp.asarray(preds.shape[0], dtype=jnp.float32)


def multilabel_ranking_loss(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """multilabel ranking loss (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_ranking_loss
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_ranking_loss(preds, target, num_labels=3)
        >>> round(float(result), 4)
        0.0
    """

    if validate_args:
        _check_same_shape(preds, target)
    preds, target = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    loss, total = _label_ranking_loss_update(preds, target)
    return loss / total
