"""Shared validate→format→update plumbing for stat-score-derived metrics.

Factors the common stages so each derived metric (precision, recall, f-beta,
specificity, hamming, jaccard, npv) is just its reducer — the reference repeats
these stages inline per metric (e.g. functional/classification/precision_recall.py).
"""
from __future__ import annotations

from typing import Optional, Tuple

from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)

StatTuple = Tuple[Array, Array, Array, Array]


def _binary_stats(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> StatTuple:
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, valid = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    return _binary_stat_scores_update(preds, target, valid, multidim_average)


def _multiclass_stats(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> StatTuple:
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    if top_k == 1:
        preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    return _multiclass_stat_scores_update(preds, target, num_classes, top_k, average, multidim_average, ignore_index)


def _multilabel_stats(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> StatTuple:
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, valid = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    return _multilabel_stat_scores_update(preds, target, valid, multidim_average)
