"""Cohen's kappa (reference functional/classification/cohen_kappa.py, 271 LoC)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
)
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


def _cohen_kappa_reduce(confmat: Array, weights: Optional[str] = None) -> Array:
    """κ from a confusion matrix with optional 'linear'/'quadratic' weighting."""
    confmat = confmat.astype(jnp.float32)
    n_classes = confmat.shape[-1]
    sum0 = confmat.sum(0, keepdims=True)
    sum1 = confmat.sum(1, keepdims=True)
    expected = sum1 @ sum0 / sum0.sum()

    if weights is None:
        w_mat = jnp.ones((n_classes, n_classes)) - jnp.eye(n_classes)
    elif weights in ("linear", "quadratic"):
        w_mat = jnp.arange(n_classes, dtype=jnp.float32)
        w_mat = jnp.abs(w_mat[:, None] - w_mat[None, :])
        if weights == "quadratic":
            w_mat = w_mat**2
    else:
        raise ValueError(f"Received an invalid value for argument `weights`, expected one of None, 'linear', 'quadratic' but got {weights}")
    k = (w_mat * confmat).sum() / (w_mat * expected).sum()
    return 1 - k


def binary_cohen_kappa(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary cohen kappa (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_cohen_kappa
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_cohen_kappa(preds, target)
        >>> round(float(result), 4)
        0.0
    """

    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, valid = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, valid)
    return _cohen_kappa_reduce(confmat, weights)


def multiclass_cohen_kappa(
    preds: Array,
    target: Array,
    num_classes: int,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass cohen kappa (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_cohen_kappa
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_cohen_kappa(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.6364
    """

    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, valid, num_classes)
    return _cohen_kappa_reduce(confmat, weights)


def cohen_kappa(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """cohen kappa (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import cohen_kappa
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = cohen_kappa(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.6364
    """

    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_cohen_kappa(preds, target, threshold, weights, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_cohen_kappa(preds, target, num_classes, weights, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
