"""Specificity (reference functional/classification/specificity.py)."""
from __future__ import annotations

from typing import Optional

from jax import Array

from torchmetrics_tpu.functional.classification._stats_helper import (
    _binary_stats,
    _multiclass_stats,
    _multilabel_stats,
)
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _specificity_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
) -> Array:
    if average == "binary":
        return _safe_divide(tn, tn + fp)
    if average == "micro":
        axis = (0 if multidim_average == "global" else 1) if tp.ndim else None
        tn = tn.sum(axis=axis)
        fp = fp.sum(axis=axis)
        return _safe_divide(tn, tn + fp)
    specificity_score = _safe_divide(tn, tn + fp)
    return _adjust_weights_safe_divide(specificity_score, average, multilabel, tp, fp, fn, top_k)


def binary_specificity(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True):
    """binary specificity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_specificity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_specificity(preds, target)
        >>> round(float(result), 4)
        0.5
    """

    tp, fp, tn, fn = _binary_stats(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_specificity(
    preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True
):
    """multiclass specificity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_specificity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_specificity(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.8889
    """

    tp, fp, tn, fn = _multiclass_stats(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, top_k=top_k)


def multilabel_specificity(
    preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True
):
    """multilabel specificity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_specificity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_specificity(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    tp, fp, tn, fn = _multilabel_stats(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def specificity(
    preds,
    target,
    task,
    threshold=0.5,
    num_classes=None,
    num_labels=None,
    average="micro",
    multidim_average="global",
    top_k=1,
    ignore_index=None,
    validate_args=True,
):
    """specificity (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import specificity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = specificity(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.875
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_specificity(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_specificity(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_specificity(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
