"""Precision & Recall (reference functional/classification/precision_recall.py)."""
from __future__ import annotations

from typing import Optional

from jax import Array

from torchmetrics_tpu.functional.classification._stats_helper import (
    _binary_stats,
    _multiclass_stats,
    _multilabel_stats,
)
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _precision_recall_reduce(
    stat: str,
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0.0,
) -> Array:
    """Reduce to precision (stat='precision': tp/(tp+fp)) or recall (tp/(tp+fn))."""
    different_stat = fp if stat == "precision" else fn
    if average == "binary":
        return _safe_divide(tp, tp + different_stat, zero_division)
    if average == "micro":
        axis = (0 if multidim_average == "global" else 1) if tp.ndim else None
        tp = tp.sum(axis=axis)
        different_stat = different_stat.sum(axis=axis)
        return _safe_divide(tp, tp + different_stat, zero_division)
    score = _safe_divide(tp, tp + different_stat, zero_division)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def _make_pr(stat: str):
    def binary_fn(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True):
        tp, fp, tn, fn = _binary_stats(preds, target, threshold, multidim_average, ignore_index, validate_args)
        return _precision_recall_reduce(stat, tp, fp, tn, fn, average="binary", multidim_average=multidim_average)

    def multiclass_fn(
        preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True
    ):
        tp, fp, tn, fn = _multiclass_stats(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
        return _precision_recall_reduce(stat, tp, fp, tn, fn, average=average, multidim_average=multidim_average, top_k=top_k)

    def multilabel_fn(
        preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True
    ):
        tp, fp, tn, fn = _multilabel_stats(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
        return _precision_recall_reduce(
            stat, tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True
        )

    return binary_fn, multiclass_fn, multilabel_fn


binary_precision, multiclass_precision, multilabel_precision = _make_pr("precision")
binary_recall, multiclass_recall, multilabel_recall = _make_pr("recall")
for _f, _n in (
    (binary_precision, "binary_precision"),
    (multiclass_precision, "multiclass_precision"),
    (multilabel_precision, "multilabel_precision"),
    (binary_recall, "binary_recall"),
    (multiclass_recall, "multiclass_recall"),
    (multilabel_recall, "multilabel_recall"),
):
    _f.__name__ = _f.__qualname__ = _n


def _dispatch(binary_fn, multiclass_fn, multilabel_fn):
    def task_fn(
        preds,
        target,
        task,
        threshold=0.5,
        num_classes=None,
        num_labels=None,
        average="micro",
        multidim_average="global",
        top_k=1,
        ignore_index=None,
        validate_args=True,
    ):
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return binary_fn(preds, target, threshold, multidim_average, ignore_index, validate_args)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return multiclass_fn(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return multilabel_fn(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
        raise ValueError(f"Not handled value: {task}")

    return task_fn


precision = _dispatch(binary_precision, multiclass_precision, multilabel_precision)
precision.__name__ = "precision"
recall = _dispatch(binary_recall, multiclass_recall, multilabel_recall)
recall.__name__ = "recall"

binary_precision.__doc__ = """binary precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_precision(preds, target)
        >>> round(float(result), 4)
        0.5
"""

binary_recall.__doc__ = """binary recall (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_recall
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_recall(preds, target)
        >>> round(float(result), 4)
        0.5
"""

multiclass_precision.__doc__ = """multiclass precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_precision(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.8333
"""

multiclass_recall.__doc__ = """multiclass recall (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_recall
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_recall(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.8333
"""

multilabel_precision.__doc__ = """multilabel precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_precision(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
"""

multilabel_recall.__doc__ = """multilabel recall (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_recall
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_recall(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
"""

precision.__doc__ = """precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = precision(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.75
"""

recall.__doc__ = """recall (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import recall
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = recall(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.75
"""
