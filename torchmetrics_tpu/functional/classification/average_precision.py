"""Average precision (reference functional/classification/average_precision.py, 467 LoC).

AP = Σ (R_n − R_{n+1}) · P_n over the PR curve from the shared state.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _ap_from_curve(precision: Array, recall: Array) -> Array:
    """AP over one (precision, recall) curve: −Σ ΔR · P."""
    return -jnp.sum(jnp.diff(recall) * precision[:-1])


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Array:
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds, pos_label)
    return _ap_from_curve(precision, recall)


def binary_average_precision(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary average precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_average_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_average_precision(preds, target)
        >>> round(float(result), 4)
        0.8333
    """

    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, valid, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, valid, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _binary_average_precision_compute(state, thresholds)


def _reduce_average_precision(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    if isinstance(precision, (list, tuple)):
        res = jnp.stack([_ap_from_curve(p, r) for p, r in zip(precision, recall)])
    else:  # (C, T+1) arrays from binned mode
        res = -jnp.sum(jnp.diff(recall, axis=1) * precision[:, :-1], axis=1)
    res = jnp.where(jnp.isnan(res), 0.0, res)
    if average in (None, "none"):
        return res
    if average == "macro":
        return res.mean()
    if average == "weighted":
        assert weights is not None
        w = _safe_divide(weights.astype(jnp.float32), weights.sum())
        return (res * w).sum()
    raise ValueError(f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None) but got {average}")


def multiclass_average_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass average precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_average_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_average_precision(preds, target, num_classes=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, valid, num_classes, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
        target_for_w = state[1]
    else:
        target_for_w = jnp.asarray(np.asarray(target)[np.asarray(valid)])
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    weights = jnp.stack([(target_for_w == c).sum() for c in range(num_classes)]).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights)


def multilabel_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multilabel average precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_average_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_average_precision(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, valid, num_labels, thresholds)
    if average == "micro":
        if state is None:
            keep = np.asarray(valid).ravel()
            return _binary_average_precision_compute(
                (jnp.asarray(np.asarray(preds).ravel()[keep]), jnp.asarray(np.asarray(target).ravel()[keep])), None
            )
        return _binary_average_precision_compute(state.sum(1), thresholds)
    if state is None:
        precision, recall, _ = _multilabel_precision_recall_curve_compute((preds, target), num_labels, None, ignore_index, valid)
    else:
        precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds)
    weights = (jnp.asarray(target) * jnp.asarray(valid)).sum(0).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights)


def average_precision(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """average precision (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import average_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = average_precision(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        1.0
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
