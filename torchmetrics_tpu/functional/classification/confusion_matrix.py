"""Confusion matrix (reference functional/classification/confusion_matrix.py, 657 LoC).

normalize ∈ {none, true, pred, all}. Counting is the flattened-bincount trick —
a single deterministic scatter-add on TPU; ``ignore_index`` handled with weights.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import _sigmoid_if_logits
from torchmetrics_tpu.utils.checks import _is_float_dtype, _check_same_shape, _is_concrete
from torchmetrics_tpu.utils.enums import ClassificationTask


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalize a (..., C, C) confusion matrix (reference confusion_matrix.py:40-60)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = confmat / confmat.sum(axis=-1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / confmat.sum(axis=-2, keepdims=True)
        elif normalize == "all":
            confmat = confmat / confmat.sum(axis=(-2, -1), keepdims=True)
        confmat = jnp.where(jnp.isnan(confmat), 0.0, confmat)
    return confmat


# --------------------------------------------------------------------- binary

def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Expected argument `normalize` to be one of {allowed_normalize}, but got {normalize}.")


def _binary_confusion_matrix_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not _is_concrete(target):
        return
    t = np.asarray(target)
    unique_values = set(np.unique(t).tolist())
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not unique_values.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique_values)} but expected only"
            f" the following values {sorted(allowed)}."
        )
    p = np.asarray(preds)
    if not _is_float_dtype(p.dtype):
        unique_p = set(np.unique(p).tolist())
        if not unique_p.issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {sorted(unique_p)} but expected only 0s and 1s."
            )


def _binary_confusion_matrix_format(
    preds: Array, target: Array, threshold: float = 0.5, ignore_index: Optional[int] = None,
    convert_to_labels: bool = True,
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = _sigmoid_if_logits(preds)
        if convert_to_labels:
            preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32) if convert_to_labels else preds
    if ignore_index is not None:
        valid = target != ignore_index
    else:
        valid = jnp.ones_like(target, dtype=bool)
    target = jnp.where(valid, target, 0).astype(jnp.int32)
    return preds, target, valid


def _binary_confusion_matrix_update(preds: Array, target: Array, valid: Array) -> Array:
    w = valid.astype(jnp.float32)
    idx = (target * 2 + preds).astype(jnp.int32)
    return jnp.zeros(4, dtype=jnp.float32).at[idx].add(w).reshape(2, 2).astype(jnp.int32)


def _binary_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def binary_confusion_matrix(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary confusion matrix (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_confusion_matrix
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_confusion_matrix(preds, target)
        >>> jnp.round(result, 4).tolist()
        [[1, 1], [1, 1]]
    """

    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, valid = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, valid)
    return _binary_confusion_matrix_compute(confmat, normalize)


# ----------------------------------------------------------------- multiclass

def _multiclass_confusion_matrix_arg_validation(
    num_classes: int, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Expected argument `normalize` to be one of {allowed_normalize}, but got {normalize}.")


def _multiclass_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
    elif preds.ndim != target.ndim:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be"
                         " (N, ...) and `preds` should be (N, C, ...).")


def _multiclass_confusion_matrix_format(
    preds: Array, target: Array, ignore_index: Optional[int] = None, convert_to_labels: bool = True
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and convert_to_labels:
        preds = preds.argmax(axis=1)
    preds = preds.reshape(-1) if convert_to_labels else preds.reshape(preds.shape[0], -1)
    target = target.reshape(-1)
    if ignore_index is not None:
        valid = target != ignore_index
    else:
        valid = jnp.ones_like(target, dtype=bool)
    target = jnp.where(valid, target, 0).astype(jnp.int32)
    return preds, target, valid


def _multiclass_confusion_matrix_update(preds: Array, target: Array, valid: Array, num_classes: int) -> Array:
    w = valid.astype(jnp.float32)
    p = jnp.clip(preds.astype(jnp.int32), 0, num_classes - 1)
    idx = (target * num_classes + p).astype(jnp.int32)
    from torchmetrics_tpu.ops import weighted_bincount

    return (
        weighted_bincount(idx, w, num_classes * num_classes)
        .reshape(num_classes, num_classes)
        .astype(jnp.int32)
    )


def _multiclass_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multiclass_confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass confusion matrix (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_confusion_matrix
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_confusion_matrix(preds, target, num_classes=3)
        >>> jnp.round(result, 4).tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """

    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, valid, num_classes)
    return _multiclass_confusion_matrix_compute(confmat, normalize)


# ----------------------------------------------------------------- multilabel

def _multilabel_confusion_matrix_arg_validation(
    num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Expected argument `normalize` to be one of {allowed_normalize}, but got {normalize}.")


def _multilabel_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )


def _multilabel_confusion_matrix_format(
    preds: Array, target: Array, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None,
    should_threshold: bool = True,
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = _sigmoid_if_logits(preds)
        if should_threshold:
            preds = (preds > threshold).astype(jnp.int32)
    preds = jnp.moveaxis(preds, 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(target, 1, -1).reshape(-1, num_labels)
    if ignore_index is not None:
        valid = target != ignore_index
    else:
        valid = jnp.ones_like(target, dtype=bool)
    target = jnp.where(valid, target, 0).astype(jnp.int32)
    preds = jnp.where(valid, preds, 0)
    return preds, target, valid


def _multilabel_confusion_matrix_update(preds: Array, target: Array, valid: Array, num_labels: int) -> Array:
    w = valid.astype(jnp.float32)
    label_idx = jnp.arange(num_labels)[None, :]
    idx = (label_idx * 4 + target * 2 + preds).astype(jnp.int32)
    from torchmetrics_tpu.ops import weighted_bincount

    out = weighted_bincount(idx.reshape(-1), w.reshape(-1), num_labels * 4)
    return out.reshape(num_labels, 2, 2).astype(jnp.int32)


def _multilabel_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multilabel_confusion_matrix(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multilabel confusion matrix (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_confusion_matrix
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_confusion_matrix(preds, target, num_labels=3)
        >>> jnp.round(result, 4).tolist()
        [[[2, 0], [0, 1]], [[1, 0], [0, 2]], [[1, 0], [0, 2]]]
    """

    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, valid, num_labels)
    return _multilabel_confusion_matrix_compute(confmat, normalize)


def confusion_matrix(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """confusion matrix (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import confusion_matrix
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = confusion_matrix(preds, target, task="multiclass", num_classes=3)
        >>> jnp.round(result, 4).tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_confusion_matrix(preds, target, threshold, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_confusion_matrix(preds, target, num_classes, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, normalize, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
