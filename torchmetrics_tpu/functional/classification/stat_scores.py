"""Stat-scores (tp/fp/tn/fn) machinery — the canonical per-metric decomposition.

Capability parity with reference functional/classification/stat_scores.py:
``_arg_validation`` → ``_tensor_validation`` → ``_format`` → ``_update`` → ``_compute``
for each of binary / multiclass / multilabel, plus the task-dispatching public
``stat_scores``. TPU-first re-design decisions:

- No data-dependent Python branching: "sigmoid if logits" becomes a traced
  ``jnp.where(any_outside_unit_interval, sigmoid(x), x)`` select; validation stages
  read concrete values and are skipped automatically under jit.
- ``ignore_index`` masking is weight-based (weighted bincount / masked sums) rather
  than boolean gather — shapes stay static.
- Multiclass counts use the flattened confusion-matrix bincount trick
  (reference stat_scores.py:217-555): ``bincount(C*target + preds, length=C*C)``,
  which XLA lowers to a deterministic scatter-add. ``top_k > 1`` uses the one-hot
  top-k mask path.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.checks import _is_float_dtype, _check_same_shape, _is_concrete
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.data import select_topk
from torchmetrics_tpu.utils.enums import ClassificationTask


def _sigmoid_if_logits(preds: Array) -> Array:
    """Apply sigmoid iff any value lies outside [0, 1] (trace-safe select)."""
    needs = jnp.any((preds < 0) | (preds > 1))
    return jnp.where(needs, jax.nn.sigmoid(preds), preds)


def _softmax_if_logits(preds: Array, axis: int = 1) -> Array:
    needs = jnp.any((preds < 0) | (preds > 1))
    return jnp.where(needs, jax.nn.softmax(preds, axis=axis), preds)


# --------------------------------------------------------------------- binary

def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    allowed_multidim_average = ("global", "samplewise")
    if multidim_average not in allowed_multidim_average:
        raise ValueError(
            f"Expected argument `multidim_average` to be one of {allowed_multidim_average}, but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_stat_scores_tensor_validation(
    preds: Array, target: Array, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not _is_concrete(target):
        return
    t = np.asarray(target)
    unique_values = np.unique(t)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not set(unique_values.tolist()).issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {sorted(allowed)}."
        )
    p = np.asarray(preds)
    if not _is_float_dtype(p.dtype):
        unique_p = set(np.unique(p).tolist())
        if not unique_p.issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {sorted(unique_p)} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError("Expected input to be at least 2D when multidim_average is set to `samplewise`")


def _binary_stat_scores_format(
    preds: Array, target: Array, threshold: float = 0.5, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Returns (preds01, target01, valid_mask), each flattened to (N, ...)-preserving shape."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = _sigmoid_if_logits(preds)
        preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    if ignore_index is not None:
        valid = (target != ignore_index)
    else:
        valid = jnp.ones_like(target, dtype=bool)
    target = jnp.where(valid, target, 0).astype(jnp.int32)
    preds = jnp.where(valid, preds, 0)
    return preds, target, valid


def _binary_stat_scores_update(
    preds: Array, target: Array, valid: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    if multidim_average == "global":
        preds, target, valid = preds.reshape(-1), target.reshape(-1), valid.reshape(-1)
        axis = 0
    else:
        preds = preds.reshape(preds.shape[0], -1)
        target = target.reshape(target.shape[0], -1)
        valid = valid.reshape(valid.shape[0], -1)
        axis = 1
    v = valid.astype(jnp.int32)
    tp = ((target == preds) & (target == 1) & valid).astype(jnp.int32).sum(axis)
    fn = ((target != preds) & (target == 1) & valid).astype(jnp.int32).sum(axis)
    fp = ((target != preds) & (target == 0) & valid).astype(jnp.int32).sum(axis)
    tn = ((target == preds) & (target == 0) & valid).astype(jnp.int32).sum(axis)
    return tp, fp, tn, fn


def _binary_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, multidim_average: str = "global"
) -> Array:
    stacked = jnp.stack([tp, fp, tn, fn, tp + fn], axis=0 if tp.ndim == 0 or multidim_average == "global" else 1)
    return stacked.squeeze() if multidim_average == "global" else stacked


def binary_stat_scores(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Compute tp/fp/tn/fn for binary tasks (reference stat_scores.py:141-214).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_stat_scores
        >>> preds = jnp.asarray([0.1, 0.9, 0.8, 0.3])
        >>> target = jnp.asarray([0, 1, 0, 1])
        >>> [int(v) for v in binary_stat_scores(preds, target)]  # tp fp tn fn sup
        [1, 1, 1, 1, 2]
    """
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, valid = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, valid, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# ----------------------------------------------------------------- multiclass

def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) and top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    allowed_multidim_average = ("global", "samplewise")
    if multidim_average not in allowed_multidim_average:
        raise ValueError(
            f"Expected argument `multidim_average` to be one of {allowed_multidim_average}, but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multiclass_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        if multidim_average != "global" and preds.ndim < 3:
            raise ValueError("If `preds` have one dimension more than `target`, the shape of `preds` should "
                             " be at least 3D when multidim_average is set to `samplewise`")
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape.")
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError("When `preds` and `target` have the same shape, the shape should be at least 2D when"
                             " multidim_average is set to `samplewise`")
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be"
                         " (N, ...) and `preds` should be (N, C, ...).")
    if not _is_concrete(target):
        return
    t = np.asarray(target)
    num_unique = np.unique(t)
    check_value = num_classes if ignore_index is None else num_classes + 1
    if len(num_unique) > check_value or (t.size and (t.max() >= num_classes and (ignore_index is None or t.max() != ignore_index))):
        raise RuntimeError(f"Detected more unique values in `target` than expected. Expected only {check_value} but found"
                           f" {len(num_unique)} in `target`.")
    p = np.asarray(preds)
    if not _is_float_dtype(p.dtype) and p.size and p.max() >= num_classes:
        raise RuntimeError(f"Detected more unique values in `preds` than expected. Expected only {num_classes} but found"
                           f" more in `preds`.")


def _multiclass_stat_scores_format(
    preds: Array, target: Array, top_k: int = 1
) -> Tuple[Array, Array]:
    """Convert probability/logit preds to labels (top_k==1) and flatten extra dims."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and top_k == 1:
        preds = preds.argmax(axis=1)
    if top_k == 1:
        preds = preds.reshape(preds.shape[0], -1) if preds.ndim > 1 else preds.reshape(preds.shape[0])
    target = target.reshape(target.shape[0], -1) if target.ndim > 1 else target.reshape(target.shape[0])
    return preds, target


def _multiclass_stat_scores_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn per class.

    top_k == 1: flattened confusion-matrix bincount (weights mask ignore_index).
    top_k > 1: one-hot top-k mask path.
    """
    if top_k > 1:
        # preds (N, C, ...) scores; build top-k mask
        preds_mask = select_topk(preds, topk=top_k, dim=1)  # (N, C, ...)
        target_oh = jax.nn.one_hot(target, num_classes, axis=1, dtype=jnp.int32)  # (N, C, ...)
        if ignore_index is not None:
            valid = (target != ignore_index)[:, None, ...]
        else:
            valid = jnp.ones_like(target, dtype=bool)[:, None, ...]
        # ignored positions contribute to NO bucket — multiply every product by
        # valid (reference stat_scores.py:374-386 excludes them via -1 rows)
        sum_axes = (0,) + tuple(range(2, preds_mask.ndim)) if multidim_average == "global" else tuple(range(2, preds_mask.ndim))
        tp = (preds_mask * target_oh * valid).sum(sum_axes)
        fp = (preds_mask * (1 - target_oh) * valid).sum(sum_axes)
        fn = ((1 - preds_mask) * target_oh * valid).sum(sum_axes)
        tn = ((1 - preds_mask) * (1 - target_oh) * valid).sum(sum_axes)
        return tp, fp, tn, fn

    # label path: confusion-matrix bincount
    if multidim_average == "global":
        p = preds.reshape(-1)
        t = target.reshape(-1)
        if ignore_index is not None:
            w = (t != ignore_index).astype(jnp.float32)
            t = jnp.where(t == ignore_index, 0, t)
        else:
            w = jnp.ones_like(t, dtype=jnp.float32)
        p = jnp.clip(p, 0, num_classes - 1)
        idx = (num_classes * t + p).astype(jnp.int32)
        confmat = jnp.zeros(num_classes * num_classes, dtype=jnp.float32).at[idx].add(w).reshape(num_classes, num_classes)
        tp = jnp.diagonal(confmat)
        fp = confmat.sum(0) - tp
        fn = confmat.sum(1) - tp
        tn = confmat.sum() - tp - fp - fn
        return tp.astype(jnp.int32), fp.astype(jnp.int32), tn.astype(jnp.int32), fn.astype(jnp.int32)

    # samplewise
    n = preds.shape[0]
    p = preds.reshape(n, -1)
    t = target.reshape(n, -1)
    if ignore_index is not None:
        w = (t != ignore_index).astype(jnp.float32)
        t = jnp.where(t == ignore_index, 0, t)
    else:
        w = jnp.ones_like(t, dtype=jnp.float32)
    p = jnp.clip(p, 0, num_classes - 1)
    sample_idx = jnp.arange(n)[:, None]
    idx = (sample_idx * num_classes * num_classes + num_classes * t + p).astype(jnp.int32)
    confmat = (
        jnp.zeros(n * num_classes * num_classes, dtype=jnp.float32)
        .at[idx.reshape(-1)]
        .add(w.reshape(-1))
        .reshape(n, num_classes, num_classes)
    )
    tp = jnp.diagonal(confmat, axis1=1, axis2=2)
    fp = confmat.sum(1) - tp
    fn = confmat.sum(2) - tp
    tn = confmat.sum((1, 2))[:, None] - tp - fp - fn
    return tp.astype(jnp.int32), fp.astype(jnp.int32), tn.astype(jnp.int32), fn.astype(jnp.int32)


def _multiclass_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str] = "macro", multidim_average: str = "global"
) -> Array:
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_axis = 0 if multidim_average == "global" else 1
    if average == "micro":
        # scalar states are already class-aggregated (reference stat_scores.py:430)
        return res.sum(sum_axis) if res.ndim > 1 else res
    if average in ("macro", "weighted"):
        res = res.astype(jnp.float32)
        weights = (tp + fn).astype(jnp.float32) if average == "weighted" else jnp.ones_like(tp, dtype=jnp.float32)
        w = _safe_divide(weights, weights.sum(-1, keepdims=True) if weights.ndim else weights.sum())
        return (res * (w[..., None] if res.ndim > w.ndim else w)).sum(sum_axis)
    return res


def multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Compute tp/fp/tn/fn for multiclass tasks (reference stat_scores.py:217-555).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_stat_scores
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_stat_scores(preds, target, num_classes=3)
        >>> jnp.round(result, 4).tolist()
        [1.0, 0.33329999446868896, 2.3332998752593994, 0.33329999446868896, 1.333299994468689]
    """
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    if top_k == 1:
        preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    tp, fp, tn, fn = _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ----------------------------------------------------------------- multilabel

def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float, but got {threshold}.")
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    allowed_multidim_average = ("global", "samplewise")
    if multidim_average not in allowed_multidim_average:
        raise ValueError(
            f"Expected argument `multidim_average` to be one of {allowed_multidim_average}, but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multilabel_stat_scores_tensor_validation(
    preds: Array, target: Array, num_labels: int, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )
    if not _is_concrete(target):
        return
    t = np.asarray(target)
    unique_values = set(np.unique(t).tolist())
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not unique_values.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique_values)} but expected only"
            f" the following values {sorted(allowed)}."
        )
    p = np.asarray(preds)
    if not _is_float_dtype(p.dtype):
        unique_p = set(np.unique(p).tolist())
        if not unique_p.issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {sorted(unique_p)} but expected only 0s and 1s since preds"
                " is a label tensor."
            )
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError("Expected input to be at least 3D when multidim_average is set to `samplewise`")


def _multilabel_stat_scores_format(
    preds: Array, target: Array, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = _sigmoid_if_logits(preds)
        preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    preds = preds.reshape(*preds.shape[:2], -1)
    target = target.reshape(*target.shape[:2], -1)
    if ignore_index is not None:
        valid = (target != ignore_index)
    else:
        valid = jnp.ones_like(target, dtype=bool)
    target = jnp.where(valid, target, 0).astype(jnp.int32)
    preds = jnp.where(valid, preds, 0)
    return preds, target, valid


def _multilabel_stat_scores_update(
    preds: Array, target: Array, valid: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    sum_axes = (0, -1) if multidim_average == "global" else (-1,)
    tp = ((target == preds) & (target == 1) & valid).astype(jnp.int32).sum(sum_axes)
    fn = ((target != preds) & (target == 1) & valid).astype(jnp.int32).sum(sum_axes)
    fp = ((target != preds) & (target == 0) & valid).astype(jnp.int32).sum(sum_axes)
    tn = ((target == preds) & (target == 0) & valid).astype(jnp.int32).sum(sum_axes)
    return tp, fp, tn, fn


def _multilabel_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str] = "macro", multidim_average: str = "global"
) -> Array:
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_axis = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(sum_axis)
    if average == "macro":
        return res.astype(jnp.float32).mean(sum_axis)
    if average == "weighted":
        # the reference normalises multilabel weights by the GLOBAL support
        # sum even samplewise (reference stat_scores.py:695-697) — unlike the
        # per-sample normalisation of the multiclass variant
        res = res.astype(jnp.float32)
        weights = (tp + fn).astype(jnp.float32)
        # plain division like the reference: zero total support yields NaN
        # there too (w / w.sum(), stat_scores.py:697) — parity over safety
        w = weights / weights.sum()
        return (res * w[..., None]).sum(sum_axis)
    return res


def multilabel_stat_scores(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Compute tp/fp/tn/fn for multilabel tasks (reference stat_scores.py:557-810).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_stat_scores
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_stat_scores(preds, target, num_labels=3)
        >>> jnp.round(result, 4).tolist()
        [1.666700005531311, 0.0, 1.333299994468689, 0.0, 1.666700005531311]
    """
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, valid = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, valid, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ------------------------------------------------------------------- dispatch

def stat_scores(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching stat scores (reference stat_scores.py public entry).

    Example:
        >>> from torchmetrics_tpu.functional import stat_scores
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = stat_scores(preds, target, task="multiclass", num_classes=3)
        >>> jnp.round(result, 4).tolist()
        [3, 1, 7, 1, 4]
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_stat_scores(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
