"""Dice score (reference functional/classification/dice.py, the legacy multi-task path).

Behavioral notes pinned against the reference (see tests/classification/test_dice.py):

- integer label inputs (binary included) evaluate as C-class one-hot stats —
  binary LABELS give the 2-class micro dice, while binary PROBABILITIES give
  the single-column dice (the legacy input-classification quirk);
- ``ignore_index`` removes that class COLUMN from the one-hot stats;
- macro averaging excludes classes absent from both preds and target;
- ``mdmc_average='global'`` flattens extra dims, ``'samplewise'`` scores each
  sample then averages.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _safe_divide


def _dice_multihot(preds: Array, target: Array, num_classes: int, top_k: Optional[int]) -> Tuple[Array, Array]:
    """Convert inputs to (N, C) multi-hot preds + one-hot target."""
    if jnp.issubdtype(preds.dtype, jnp.floating):
        if preds.ndim == target.ndim + 1:
            # (N, C) class probabilities/logits
            if top_k is not None and top_k > 1:
                order = jnp.argsort(-preds, axis=1)[:, :top_k]
                ph = jnp.zeros((preds.shape[0], num_classes), dtype=jnp.int32)
                ph = ph.at[jnp.arange(preds.shape[0])[:, None], order].set(1)
            else:
                ph = (preds.argmax(axis=1)[:, None] == jnp.arange(num_classes)[None, :]).astype(jnp.int32)
        else:
            raise ValueError("float preds must have one extra class dimension for multiclass dice")
    else:
        ph = (preds[:, None] == jnp.arange(num_classes)[None, :]).astype(jnp.int32)
    th = (target[:, None] == jnp.arange(num_classes)[None, :]).astype(jnp.int32)
    return ph, th


def _dice_stats(
    preds: Array,
    target: Array,
    threshold: float,
    top_k: Optional[int],
    num_classes: Optional[int],
    ignore_index: Optional[int],
) -> Tuple[Array, Array, Array]:
    """Per-class (tp, fp, fn) of shape (C,) — or (1,) for binary-probability input."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target).astype(jnp.int32)

    if jnp.issubdtype(preds.dtype, jnp.floating) and preds.ndim == target.ndim:
        # binary probabilities -> single column (legacy "binary" case)
        if bool(jnp.any((preds < 0) | (preds > 1))):
            preds = 1.0 / (1.0 + jnp.exp(-preds))
        p = (preds > threshold).astype(jnp.int32).reshape(-1)
        t = target.reshape(-1)
        if ignore_index is not None:
            keep = t != ignore_index
            p, t = p[keep], t[keep]
        tp = jnp.sum(p * t)[None]
        fp = jnp.sum(p * (1 - t))[None]
        fn = jnp.sum((1 - p) * t)[None]
        return tp, fp, fn

    if num_classes is None:
        num_classes = int(jnp.maximum(preds.max() if not jnp.issubdtype(preds.dtype, jnp.floating) else 0, target.max())) + 1
        if jnp.issubdtype(preds.dtype, jnp.floating):
            num_classes = preds.shape[1]

    ph, th = _dice_multihot(preds.reshape(-1) if not jnp.issubdtype(preds.dtype, jnp.floating) else preds, target.reshape(-1), num_classes, top_k)
    tp = jnp.sum(ph * th, axis=0)
    fp = jnp.sum(ph * (1 - th), axis=0)
    fn = jnp.sum((1 - ph) * th, axis=0)
    if ignore_index is not None:
        if not 0 <= ignore_index < num_classes:
            raise ValueError(f"ignore_index {ignore_index} is not in [0, {num_classes})")
        keep = jnp.arange(num_classes) != ignore_index
        tp, fp, fn = tp[keep], fp[keep], fn[keep]
    return tp, fp, fn


def _dice_reduce(tp: Array, fp: Array, fn: Array, average: Optional[str], zero_division: float) -> Array:
    if average == "micro":
        denom = 2 * tp.sum() + fp.sum() + fn.sum()
        return jnp.where(denom == 0, float(zero_division), 2 * tp.sum() / jnp.where(denom == 0, 1, denom))
    denom = 2 * tp + fp + fn
    scores = jnp.where(denom == 0, float(zero_division), 2 * tp / jnp.where(denom == 0, 1, denom))
    if average in (None, "none"):
        return scores
    meaningful = (tp + fp + fn) > 0
    if average == "macro":
        return _safe_divide(jnp.sum(jnp.where(meaningful, scores, 0.0)), jnp.sum(meaningful))
    if average == "weighted":
        weights = (tp + fn).astype(jnp.float32)
        return _safe_divide(jnp.sum(weights * scores), jnp.sum(weights))
    raise ValueError(f"Unsupported average {average}")


def dice(
    preds: Array,
    target: Array,
    zero_division: float = 0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Dice = 2*TP / (2*TP + FP + FN) with the legacy averaging options.

    Example:
        >>> from torchmetrics_tpu.functional import dice
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = dice(preds, target)
        >>> round(float(result), 4)
        0.75
    """
    allowed = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed:
        raise ValueError(f"The `average` has to be one of {allowed}, got {average}.")
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    is_float = jnp.issubdtype(preds.dtype, jnp.floating)
    extra_dims = preds.ndim > 1 + (1 if is_float and preds.ndim == target.ndim + 1 else 0)

    if extra_dims and mdmc_average == "samplewise" or average == "samples":
        # per-sample reduction, then mean across samples
        if is_float and preds.ndim == target.ndim + 1 and preds.ndim > 2:
            raise NotImplementedError("samplewise dice with probabilistic multidim preds is not supported")
        n = preds.shape[0]
        inner_avg = "micro" if average == "samples" else average
        vals = [
            _dice_reduce(
                *_dice_stats(preds[i].reshape(-1) if not is_float else preds[i], target[i].reshape(-1), threshold, top_k, num_classes, ignore_index),
                inner_avg,
                zero_division,
            )
            for i in range(n)
        ]
        return jnp.mean(jnp.stack(vals), axis=0)

    if extra_dims:  # mdmc global: flatten extra dims
        if is_float and preds.ndim == target.ndim + 1:
            c = preds.shape[1]
            preds = jnp.moveaxis(preds, 1, -1).reshape(-1, c)
            target = target.reshape(-1)
        else:
            preds = preds.reshape(-1)
            target = target.reshape(-1)
    _check_same_shape(preds if not (is_float and preds.ndim == target.ndim + 1) else target, target)

    tp, fp, fn = _dice_stats(preds, target, threshold, top_k, num_classes, ignore_index)
    return _dice_reduce(tp, fp, fn, average, zero_division)
