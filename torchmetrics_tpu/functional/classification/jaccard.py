"""Jaccard index / IoU (reference functional/classification/jaccard.py)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification._stats_helper import (
    _binary_stats,
    _multiclass_stats,
    _multilabel_stats,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _jaccard_index_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    ignore_index: Optional[int] = None,
) -> Array:
    """Jaccard = tp / (tp + fp + fn), class-averaged per ``average``.

    For "macro", classes absent from both preds and target (union == 0) are
    excluded from the mean; an in-range ``ignore_index`` class is excluded from
    every average (reference jaccard.py:69-91 subtracts its denominator).
    """
    if average == "binary":
        return _safe_divide(tp, tp + fp + fn)
    keep = jnp.ones_like(tp, dtype=bool)
    if ignore_index is not None and tp.ndim >= 1 and 0 <= ignore_index < tp.shape[-1]:
        keep = jnp.arange(tp.shape[-1]) != ignore_index
    if average == "micro":
        tp_s = (tp * keep).sum()
        union = ((tp + fp + fn) * keep).sum()
        return _safe_divide(tp_s, union)
    scores = _safe_divide(tp, tp + fp + fn)
    if average in ("macro", None, "none"):
        if average in (None, "none"):
            return scores
        present = ((tp + fp + fn) > 0) & keep
        return _safe_divide((scores * present).sum(-1), present.sum(-1))
    # weighted
    weights = (tp + fn).astype(jnp.float32) * keep
    return _safe_divide((scores * weights).sum(-1), weights.sum(-1))


def binary_jaccard_index(preds, target, threshold=0.5, ignore_index=None, validate_args=True):
    """binary jaccard index (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_jaccard_index
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_jaccard_index(preds, target)
        >>> round(float(result), 4)
        0.3333
    """

    tp, fp, tn, fn = _binary_stats(preds, target, threshold, "global", ignore_index, validate_args)
    return _jaccard_index_reduce(tp, fp, tn, fn, average="binary")


def multiclass_jaccard_index(preds, target, num_classes, average="macro", ignore_index=None, validate_args=True):
    """multiclass jaccard index (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_jaccard_index
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_jaccard_index(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.6667
    """

    tp, fp, tn, fn = _multiclass_stats(preds, target, num_classes, average, 1, "global", ignore_index, validate_args)
    return _jaccard_index_reduce(tp, fp, tn, fn, average=average, ignore_index=ignore_index)


def multilabel_jaccard_index(preds, target, num_labels, threshold=0.5, average="macro", ignore_index=None, validate_args=True):
    """multilabel jaccard index (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_jaccard_index
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_jaccard_index(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    tp, fp, tn, fn = _multilabel_stats(preds, target, num_labels, threshold, average, "global", ignore_index, validate_args)
    return _jaccard_index_reduce(tp, fp, tn, fn, average=average)


def jaccard_index(
    preds,
    target,
    task,
    threshold=0.5,
    num_classes=None,
    num_labels=None,
    average="macro",
    ignore_index=None,
    validate_args=True,
):
    """jaccard index (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import jaccard_index
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = jaccard_index(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.6667
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_jaccard_index(preds, target, threshold, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_jaccard_index(preds, target, num_classes, average, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_jaccard_index(preds, target, num_labels, threshold, average, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
