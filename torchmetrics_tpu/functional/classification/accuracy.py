"""Accuracy (reference functional/classification/accuracy.py)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification._stats_helper import (
    _binary_stats,
    _multiclass_stats,
    _multilabel_stats,
)
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _accuracy_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
) -> Array:
    """Reduce stat scores into accuracy (reference accuracy.py:22-80)."""
    if average == "binary":
        return _safe_divide(tp + tn, tp + tn + fp + fn)
    if average == "micro":
        axis = (0 if multidim_average == "global" else 1) if tp.ndim else None
        tp = tp.sum(axis=axis)
        fn = fn.sum(axis=axis)
        if multilabel:
            fp = fp.sum(axis=axis)
            tn = tn.sum(axis=axis)
            return _safe_divide(tp + tn, tp + tn + fp + fn)
        return _safe_divide(tp, tp + fn)
    score = _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else _safe_divide(tp, tp + fn)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def binary_accuracy(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary accuracy (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_accuracy
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_accuracy(preds, target)
        >>> round(float(result), 4)
        0.5
    """

    tp, fp, tn, fn = _binary_stats(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_accuracy(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass accuracy (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_accuracy
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_accuracy(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.8333
    """

    tp, fp, tn, fn = _multiclass_stats(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, top_k=top_k)


def multilabel_accuracy(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multilabel accuracy (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_accuracy
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_accuracy(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    tp, fp, tn, fn = _multilabel_stats(
        preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def accuracy(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching accuracy.

    Example:
        >>> from torchmetrics_tpu.functional import accuracy
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = accuracy(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.75
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_accuracy(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_accuracy(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_accuracy(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
