"""ROC curve (reference functional/classification/roc.py), built on the PR-curve state."""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_clf_curve,
    _macro_interp_merge,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _binary_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """(fpr, tpr, thresholds) with fpr ascending."""
    if thresholds is not None and not isinstance(state, tuple):
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        tns = state[:, 0, 0]
        # binned mode returns exactly T points, no synthetic (0, 0) endpoint
        # (reference roc.py:45-52)
        tpr = jnp.flip(_safe_divide(tps, tps + fns), 0)
        fpr = jnp.flip(_safe_divide(fps, fps + tns), 0)
        return fpr, tpr, jnp.flip(thresholds, 0)
    preds, target = state
    fps, tps, thresh = (np.asarray(x) for x in _binary_clf_curve(preds, target))
    # prepend a (0, 0) point at threshold 1.0 (reference roc.py:55-58)
    tps = np.hstack([[0.0], tps])
    fps = np.hstack([[0.0], fps])
    thresh = np.hstack([[1.0], thresh])
    with np.errstate(divide="ignore", invalid="ignore"):
        tpr = np.nan_to_num(tps / tps[-1]) if tps[-1] != 0 else np.zeros_like(tps)
        fpr = np.nan_to_num(fps / fps[-1]) if fps[-1] != 0 else np.zeros_like(fps)
    return jnp.asarray(fpr, dtype=jnp.float32), jnp.asarray(tpr, dtype=jnp.float32), jnp.asarray(thresh)


def binary_roc(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """binary roc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_roc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_roc(preds, target)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [[0.0, 0.0, 0.5, 0.5, 1.0], [0.0, 0.5, 0.5, 1.0, 1.0], [1.0, 0.7999999523162842, 0.5999999642372131, 0.29999998211860657, 0.19999998807907104]]
    """

    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, valid, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, valid, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _binary_roc_compute(state, thresholds)


def _multiclass_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    average: Optional[str] = None,
):
    if average == "micro":
        return _binary_roc_compute(state, thresholds)
    if thresholds is not None and not isinstance(state, tuple):
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        # exactly T points per class, no synthetic (0, 0) endpoint
        # (reference roc.py:171-178)
        tpr = jnp.flip(_safe_divide(tps, tps + fns), 0).T
        fpr = jnp.flip(_safe_divide(fps, fps + tns), 0).T
        if average == "macro":
            return _macro_interp_merge(fpr, tpr, jnp.tile(thresholds, num_classes), descending=True)
        return fpr, tpr, jnp.flip(thresholds, 0)
    preds, target = state
    fpr_list, tpr_list, thresh_list = [], [], []
    for c in range(num_classes):
        f, t, th = _binary_roc_compute((preds[:, c], (target == c).astype(jnp.int32)), None)
        fpr_list.append(f)
        tpr_list.append(t)
        thresh_list.append(th)
    if average == "macro":
        return _macro_interp_merge(fpr_list, tpr_list, jnp.concatenate(thresh_list), descending=True)
    return fpr_list, tpr_list, thresh_list


def multiclass_roc(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """multiclass roc (functional interface).

    ``average``: ``"micro"`` one-hot-flattens into a single binary ROC;
    ``"macro"`` interpolation-merges the per-class curves (reference roc.py:207-215).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_roc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_roc(preds, target, num_classes=3, thresholds=5)
        >>> [tuple(v.shape) for v in result]
        [(3, 5), (3, 5), (5,)]
    """

    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    state = _multiclass_precision_recall_curve_update(preds, target, valid, num_classes, thresholds, average)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _multiclass_roc_compute(state, num_classes, thresholds, average)


def _multilabel_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    valid: Optional[Array] = None,
):
    if thresholds is not None and not isinstance(state, tuple):
        return _multiclass_roc_compute(state, num_labels, thresholds)
    preds, target = state
    fpr_list, tpr_list, thresh_list = [], [], []
    for lbl in range(num_labels):
        p_l = np.asarray(preds[:, lbl])
        t_l = np.asarray(target[:, lbl])
        if valid is not None:
            keep = np.asarray(valid[:, lbl])
            p_l, t_l = p_l[keep], t_l[keep]
        f, t, th = _binary_roc_compute((jnp.asarray(p_l), jnp.asarray(t_l)), None)
        fpr_list.append(f)
        tpr_list.append(t)
        thresh_list.append(th)
    return fpr_list, tpr_list, thresh_list


def multilabel_roc(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """multilabel roc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_roc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_roc(preds, target, num_labels=3, thresholds=5)
        >>> [tuple(v.shape) for v in result]
        [(3, 5), (3, 5), (5,)]
    """

    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, valid, num_labels, thresholds)
    if state is None:
        return _multilabel_roc_compute((preds, target), num_labels, None, valid)
    return _multilabel_roc_compute(state, num_labels, thresholds)


def roc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """roc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import roc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = roc(preds, target, task="binary", thresholds=5)
        >>> [tuple(v.shape) for v in result]
        [(5,), (5,), (5,)]
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_roc(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_roc(
            preds, target, num_classes, thresholds, ignore_index=ignore_index, validate_args=validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_roc(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
