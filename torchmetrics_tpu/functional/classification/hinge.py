"""Hinge loss (reference functional/classification/hinge.py, 289 LoC)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import _sigmoid_if_logits, _softmax_if_logits
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


def _hinge_loss_compute(measure: Array, total: Array) -> Array:
    return measure / total


def _binary_hinge_loss_arg_validation(squared: bool, ignore_index: Optional[int] = None) -> None:
    if not isinstance(squared, bool):
        raise ValueError(f"Expected argument `squared` to be an bool but got {squared}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_hinge_loss_update(preds: Array, target: Array, squared: bool) -> Tuple[Array, Array]:
    target = target * 2 - 1  # {0,1} → {-1,1}
    margin = 1 - target * preds
    losses = jnp.where(margin > 0, margin, 0.0)
    if squared:
        losses = losses**2
    return losses.sum(), jnp.asarray(losses.size, dtype=jnp.float32)


def binary_hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary hinge loss (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_hinge_loss
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_hinge_loss(preds, target)
        >>> round(float(result), 4)
        0.925
    """

    if validate_args:
        _binary_hinge_loss_arg_validation(squared, ignore_index)
    import numpy as np

    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1)
    # sigmoid-if-logits, like the reference's confusion-matrix format with
    # convert_to_labels=False (reference hinge.py:118-120)
    preds = _sigmoid_if_logits(preds)
    if ignore_index is not None:
        keep = np.asarray(target != ignore_index)
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    measures, total = _binary_hinge_loss_update(preds, target, squared)
    return _hinge_loss_compute(measures, total)


def _multiclass_hinge_loss_update(
    preds: Array, target: Array, num_classes: int, squared: bool, multiclass_mode: str
) -> Tuple[Array, Array]:
    target_oh = jax.nn.one_hot(target, num_classes, dtype=jnp.bool_)
    if multiclass_mode == "crammer-singer":
        margin = jnp.where(target_oh, preds, -jnp.inf).max(-1) - jnp.where(target_oh, -jnp.inf, preds).max(-1)
        losses = jnp.where(1 - margin > 0, 1 - margin, 0.0)
        if squared:
            losses = losses**2
        return losses.sum(), jnp.asarray(losses.size, dtype=jnp.float32)
    # one-vs-all
    t = jnp.where(target_oh, 1.0, -1.0)
    margin = 1 - t * preds
    losses = jnp.where(margin > 0, margin, 0.0)
    if squared:
        losses = losses**2
    return losses.sum(0), jnp.asarray(losses.shape[0], dtype=jnp.float32)


def multiclass_hinge_loss(
    preds: Array,
    target: Array,
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass hinge loss (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_hinge_loss
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_hinge_loss(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.625
    """

    if validate_args:
        if multiclass_mode not in ("crammer-singer", "one-vs-all"):
            raise ValueError(
                f"Expected argument `multiclass_mode` to be one of 'crammer-singer', 'one-vs-all' but got {multiclass_mode}"
            )
        _binary_hinge_loss_arg_validation(squared, ignore_index)
    import numpy as np

    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1)
    preds = _softmax_if_logits(preds, axis=-1)  # reference hinge.py multiclass format
    if ignore_index is not None:
        keep = np.asarray(target != ignore_index)
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    measures, total = _multiclass_hinge_loss_update(preds, target, num_classes, squared, multiclass_mode)
    return _hinge_loss_compute(measures, total)


def hinge_loss(
    preds: Array,
    target: Array,
    task: str,
    num_classes: Optional[int] = None,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """hinge loss (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import hinge_loss
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = hinge_loss(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.625
    """

    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_hinge_loss(preds, target, squared, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_hinge_loss(preds, target, num_classes, squared, multiclass_mode, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
